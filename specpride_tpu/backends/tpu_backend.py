"""TPU execution backend: drives the device kernels over packed batches.

Mirrors the numpy-oracle driver API (``backends.numpy_backend.run_*``) with
the same semantics, but executes each packed batch (``data.packed``) as one
jitted XLA program on the default JAX backend (TPU on real hardware; CPU —
incl. a forced multi-device CPU mesh — in tests).  Host responsibilities:
float64 m/z quantization (``ops.quantize`` / pack-time dedup), precursor/RT
estimators and medoid finalize (tiny, f64-exact), unpadding, and reassembly
into the caller's original cluster order.

Dispatch discipline (host link is latency- and bandwidth-bound): all chunks
are dispatched asynchronously before any result is collected, each kernel
returns ONE fused array per dispatch, and output buffers are sized by exact
host-computed bounds so the device→host transfer carries only real bytes.
Memory is bounded by chunking each batch along the cluster axis under
``max_grid_elements``; phantom rows from chunk padding are masked out and
never read back.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from specpride_tpu.config import (
    BatchConfig,
    BestSpectrumConfig,
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.ops import quantize
from specpride_tpu.backends import numpy_backend
from specpride_tpu.observability import (
    MetricsRegistry,
    NullJournal,
    RunStats,
    logger,
)
from specpride_tpu.observability import tracing
# fault-injection sites (specpride_tpu.robustness): zero-cost no-ops
# unless a FaultPlan is armed — the chaos harness fires realistic device
# errors exactly where production ones surface
from specpride_tpu.robustness import faults


def _ensure_compile_cache() -> None:
    """Point JAX at a persistent compilation cache (once per process).

    Kernel shapes are bounded to a few size classes precisely so compiled
    programs can be REUSED — but without a persistent cache every new
    process pays the full XLA compile bill again (15-25 s per method on
    the 2000-cluster bench).  Resolution and hit/miss accounting live in
    ``warmstart.cache`` (the CLI's ``--compile-cache DIR|off`` overrides
    this default resolution, which honors JAX_COMPILATION_CACHE_DIR /
    an already-configured jax / SPECPRIDE_JAX_CACHE)."""
    from specpride_tpu.warmstart import cache

    cache.ensure_default_compile_cache()


def _cpu_only_devices() -> bool:
    """True when every visible jax device is a CPU — i.e. there is no
    accelerator for a 'device' layout to win on (the platform list is
    cached by jax, so repeated calls are cheap)."""
    import jax

    try:
        devices = jax.devices()
    except Exception:  # noqa: BLE001 - bring-up failure: decide nothing
        return False
    return bool(devices) and all(d.platform == "cpu" for d in devices)


def _chunk_ranges(b: int, chunk: int):
    for start in range(0, b, chunk):
        yield start, min(start + chunk, b)


def _pow2(n: int, floor: int = 1) -> int:
    """Round up to a power of two (>= floor).  Every value that feeds a
    static jit argument or a padded array shape goes through this: distinct
    shapes cost one XLA compile each, so bounding them to powers of two
    keeps the compile count logarithmic instead of per-batch (the round-1
    bench spent 47 s compiling one-off shapes)."""
    return max(floor, 1 << (max(n, 1) - 1).bit_length())


def _check_no_empty(clusters: list[Cluster]) -> None:
    """Zero-member clusters are rejected up front on every device driver so
    bucket-skipping can never silently misalign outputs against inputs (the
    numpy oracle raises for gap-average and medoid; for bin-mean it returns a
    degenerate NaN-precursor spectrum — we raise there too, documented
    divergence)."""
    for c in clusters:
        if c.n_members == 0:
            raise ValueError(f"empty cluster {c.cluster_id!r}")


def _iter_compacted(fused, cap: int, n_rows: int):
    """Split a fused ``[flat_mz (cap) | flat_intensity (cap) | n_out (B)]``
    device buffer (the globally-compacted layout of
    ``ops.binning.bin_mean_deduped_compact`` /
    ``ops.gap_average.gap_average_compact``) into per-row f64 (mz, intensity)
    slices.  Rows are row-major in dispatch order; padded phantom rows emit
    ``n_out == 0`` and sit past ``n_rows``, so they are never yielded."""
    fused = np.asarray(fused)
    flat_mz = fused[:cap]
    flat_int = fused[cap : 2 * cap]
    n_out = fused[2 * cap :].astype(np.int64)
    offsets = np.concatenate([[0], np.cumsum(n_out)])
    for ci in range(n_rows):
        o0, o1 = int(offsets[ci]), int(offsets[ci + 1])
        yield ci, flat_mz[o0:o1].astype(np.float64), flat_int[o0:o1].astype(
            np.float64
        )


_fetch_pool = None
_fetch_pool_lock = threading.Lock()


def _get_fetch_pool():
    """Process-wide bounded fetch pool (3 workers): the D2H link carries
    one transfer at a time anyway, so per-chunk threads only add
    contention — a many-chunk run used to spawn one thread per chunk all
    fighting for the same link."""
    global _fetch_pool
    with _fetch_pool_lock:
        if _fetch_pool is None:
            import concurrent.futures

            _fetch_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=3, thread_name_prefix="specpride-fetch"
            )
        return _fetch_pool


class _AsyncFetch:
    """Device->host fetch driven by the bounded background pool.

    ``copy_to_host_async`` alone does NOT stream on tunneled hosts — the
    transfer only progresses inside the blocking ``np.asarray`` — but that
    block releases the GIL, so a pool worker hides the copy behind host
    pack work (measured: a 16 MB fetch fully disappears behind 1 s of
    numpy work).  Exceptions re-raise on ``get()``."""

    def __init__(self, device_array):
        self._fut = _get_fetch_pool().submit(np.asarray, device_array)

    def get(self) -> np.ndarray:
        faults.check("d2h")
        return self._fut.result()


def _cap_class(n: int, floor: int = 1) -> int:
    """Round up to a HALF-OCTAVE size class {2^k, 3*2^(k-1)} (>= floor).

    Output buffers ride a ~25 MB/s device->host link, so the pow2 padding
    of ``_pow2`` (up to 2x, ~1.4x expected) is real wall-clock; half-octave
    classes bound the overpad at 33% (~17% expected) for one extra XLA
    compile per octave (amortized by the persistent compilation cache)."""
    n = max(n, 1)
    p = 1 << (n - 1).bit_length()  # next pow2 >= n
    if n <= 3 * (p // 4):  # 1.5 * previous octave also covers n
        p = 3 * (p // 4)
    return max(floor, p)


def _max_run_len(sorted_keys: np.ndarray) -> int:
    """Longest run of equal consecutive values (keys pre-sorted)."""
    if sorted_keys.size == 0:
        return 1
    bounds = np.flatnonzero(
        np.concatenate(([True], sorted_keys[1:] != sorted_keys[:-1], [True]))
    )
    return int(np.diff(bounds).max(initial=1))


def _pad_axis0(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    if arr.shape[0] == size:
        return arr
    pad = [(0, size - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad, constant_values=fill)


@dataclasses.dataclass
class PreparedChunk:
    """Host-side pack product of ``TpuBackend.prepare_chunk`` — phase 1 of
    the two-phase chunk protocol the pipelined CLI executor drives.

    Everything in ``data`` is pure host numpy output (packed batches,
    cosine member prep, ordered-peak views): building it touches no device
    and no backend mutable state beyond the ``stats`` object the caller
    passed, so it is safe to construct on the executor's background packer
    thread while the consumer thread dispatches the previous chunk.
    ``run_prepared`` consumes it on the dispatch thread."""

    method: str  # "bin-mean" | "gap-average" | "medoid"
    kind: str  # concrete execution path the data was packed for
    clusters: list
    config: object
    cos_config: object | None = None
    data: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TpuBackend:
    """Device-execution backend (``--backend=tpu``).

    ``batch_config`` controls bucketing; ``max_grid_elements`` bounds the
    largest device intermediate per dispatch (default ~64M f32 = 256 MB).
    ``mesh`` (optional): a 1-D ``jax.sharding.Mesh`` (``parallel.cluster_mesh``)
    — every dispatch is then padded to a multiple of the mesh size and its
    inputs sharded along the cluster axis, so XLA SPMD-partitions the kernels
    across all devices with no hot-loop collectives.
    """

    batch_config: BatchConfig = dataclasses.field(default_factory=BatchConfig)
    max_grid_elements: int = 64 * 1024 * 1024
    mesh: object | None = None  # jax.sharding.Mesh
    # mesh-less layout selection: "auto" = flat zero-padding paths (and the
    # host gap path); "bucketized" forces the (B, K) device paths that mesh
    # runs use — the escape hatch if a flat path regresses (with a mesh the
    # bucketized layout is always used: a flat peak axis cannot shard
    # along clusters)
    layout: str = "auto"  # "auto" | "flat" | "bucketized"
    # always-on phase timers (pack / dispatch / d2h / finalize; plus
    # "device" when ``sync_timing``).  One RunStats accumulates across calls;
    # bench.py reads and resets it per method run.
    stats: RunStats = dataclasses.field(default_factory=RunStats)
    # bench-only: block after dispatch so "device" (H2D+kernel) and "d2h"
    # (pure transfer) time apart.  Off by default — each block is a tunnel
    # round trip (~0.1 s measured).
    sync_timing: bool = False
    # telemetry sinks (observability subsystem): per-kernel compile /
    # dispatch / padding / byte counters, and the run-journal event stream.
    # The CLI points ``journal`` at its --journal file; both default to
    # no-ops so library use pays only dict bumps.
    metrics: MetricsRegistry = dataclasses.field(
        default_factory=MetricsRegistry, repr=False
    )
    journal: object = dataclasses.field(
        default_factory=NullJournal, repr=False
    )
    # keep --mesh/--layout device kernels selected even when jax exposes
    # only CPU devices.  By default gap-average re-routes to the
    # vectorized host consensus there (BENCH_r07: the CPU 'device' path
    # ran at ~0.3x of numpy) and journals the decision; tests that
    # exercise the kernels themselves set this.
    force_device: bool = False
    # medoid: finalize the winning member index ON DEVICE and fetch one
    # int32 per cluster instead of the (B, M, M) uint16 count matrices
    # (device f32 finalize; see ops.similarity.medoid_select_packed for
    # the tie semantics).  False restores the host-f64 count finalize.
    medoid_device_select: bool = True
    # pack-waste accounting is an O(rows*k) host reduction per dispatch
    # (the lazy ``real_elems`` callables below), so it runs only when the
    # numbers are consumed: a journal is attached, or the CLI flips this
    # on for --metrics-out.  Bare library use pays only dict bumps.
    pack_accounting: bool = False
    # (kernel, shape-class) combos dispatched by THIS backend — a new combo
    # is a fresh XLA trace, i.e. a compile (an upper bound: the persistent
    # on-disk cache may turn it into a cache load)
    _seen_shapes: set = dataclasses.field(
        default_factory=set, repr=False
    )
    # per-(method, platform) execution-path table (host-vectorized /
    # xla / pallas), seeded from measured static defaults + an optional
    # bench-derived override file (warmstart.routing).  None = load the
    # default table (SPECPRIDE_ROUTING env override honored).
    routing: object = None
    # serving worker pool: the jax Device this backend's lane is pinned
    # to (None = process default).  The pin itself is applied by the
    # daemon via serve.placement.device_scope around job execution
    # (jax.default_device is thread-scoped); the backend reads this only
    # to attribute device-memory telemetry to the right device.
    device: object = None
    # reduced-precision packed paths (--precision): "f32" (default —
    # byte-parity with every pre-precision run), "bf16", or "int8".
    # Non-f32 quantizes the packed intensity channel at pack/ship time
    # (plus bf16 m/z where the round trip is pack-time-verified exact,
    # and exact int16 narrowing of index channels), routes the affected
    # methods onto their DEVICE paths (the host paths ship no bytes to
    # save), and is validated per run against the f32 oracle by the
    # CLI's QC-cosine tolerance gate (cli._precision_gate).
    precision: str = "f32"
    # buffer donation on the chunk loop (--no-donate disables): every
    # kernel call donates its packed input buffers — they are consumed
    # exactly once per dispatch — so XLA may alias them into outputs
    # instead of holding both live.  No-op on CPU/interpreter backends
    # (parity-tested); the jit twins live beside each kernel
    # (ops.jit_util.jit_pair).
    donate: bool = True
    # (method, path) routing decisions already journaled/logged — a
    # chunked run must not spam one event per chunk
    _routing_noted: set = dataclasses.field(
        default_factory=set, repr=False
    )
    # (method,) precision encodings already journaled — once per backend
    _precision_noted: set = dataclasses.field(
        default_factory=set, repr=False
    )

    def __post_init__(self):
        _ensure_compile_cache()
        if self.precision not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"precision must be f32|bf16|int8, got {self.precision!r}"
            )
        # donation resolves OFF on CPU-only hosts: the CPU backend maps
        # host numpy arrays zero-copy, so a "donated" input can alias
        # memory the host frees/reuses right after the call — measured
        # as denormal garbage in the first dispatches of a run.  On
        # accelerators the H2D copy makes the device buffer jax-owned
        # and aliasing it into outputs is the whole point.
        self._donate_effective = self.donate and not _cpu_only_devices()
        if self.donate and not self._donate_effective:
            logger.debug(
                "buffer donation disabled: cpu-only jax devices map host "
                "buffers zero-copy (no device memory to reclaim)"
            )
        if self.routing is None:
            from specpride_tpu.warmstart.routing import RoutingTable

            self.routing = RoutingTable.load()

    def _kfn(self, plain, donated):
        """The kernel callable this backend's donation setting selects —
        one jit cache per run, so the persistent compile cache never
        pays for both aliasing specs."""
        return donated if self._donate_effective else plain

    def _note_precision(self, method: str, **channels) -> None:
        """Journal/log the packed-channel encodings a reduced-precision
        run actually shipped for ``method`` — once per DISTINCT
        encoding set per backend.  The pack-time probes (bf16-exact
        m/z, int16-fitting grids) decide per batch, so a run whose
        batches diverge (e.g. one batch's m/z fails the exactness
        probe) journals each combination it actually sent — the
        operator must be able to see what was on the wire without
        diffing byte counters."""
        key = (method, tuple(sorted(channels.items())))
        if self.precision == "f32" or key in self._precision_noted:
            return
        self._precision_noted.add(key)
        enc = " ".join(f"{k}={v}" for k, v in sorted(channels.items()))
        logger.info(
            "precision %s: %s packed channels: %s",
            self.precision, method, enc,
        )
        self.journal.emit(
            "precision", method=method, precision=self.precision,
            **channels,
        )

    # -- telemetry hooks ------------------------------------------------

    def _note_dispatch(
        self, kernel: str, shape_key: tuple, *, rows: int, padded_rows: int,
        real_elems=None, padded_elems: int | None = None,
        seconds: float | None = None, t_start: float | None = None,
    ) -> None:
        """Record one device dispatch: per-kernel dispatch/compile counters,
        bucket occupancy (real vs padded rows), pack padding waste (real vs
        padded elements), dispatch-call latency, the journal events an
        operator tails (``compile`` once per new shape class, ``dispatch``
        per call), and — when a tracer is installed — one ``kernel:<name>``
        span per dispatch, annotated with the bucket shape class,
        compile-vs-cached, and real/padded element counts (``t_start`` is
        the ``perf_counter`` at dispatch start, so the span lands inside
        the "dispatch" phase span that contained the call).

        ``real_elems`` may be a zero-arg callable deferring an expensive
        host reduction; it is evaluated only when pack accounting is on."""
        m = self.metrics
        if callable(real_elems):
            real_elems = (
                int(real_elems())
                if getattr(self.journal, "enabled", True)
                or self.pack_accounting
                else None
            )
        key = (kernel, *shape_key)
        is_new_shape = key not in self._seen_shapes
        if is_new_shape:
            self._seen_shapes.add(key)
            m.counter(
                "specpride_compiles_total",
                "XLA compiles: first dispatch of a (kernel, shape-class)",
                labels=("kernel",),
            ).inc(1, kernel=kernel)
            self.journal.emit(
                "compile", kernel=kernel, shape_key=list(shape_key)
            )
        m.counter(
            "specpride_dispatches_total", "device kernel dispatches",
            labels=("kernel",),
        ).inc(1, kernel=kernel)
        m.counter(
            "specpride_rows_real_total",
            "real cluster rows dispatched", labels=("kernel",),
        ).inc(rows, kernel=kernel)
        m.counter(
            "specpride_rows_padded_total",
            "dispatched cluster rows incl. shape padding",
            labels=("kernel",),
        ).inc(padded_rows, kernel=kernel)
        if real_elems is not None and padded_elems:
            m.counter(
                "specpride_pack_real_elements_total",
                "real packed elements shipped", labels=("kernel",),
            ).inc(int(real_elems), kernel=kernel)
            m.counter(
                "specpride_pack_padded_elements_total",
                "packed elements shipped incl. padding", labels=("kernel",),
            ).inc(int(padded_elems), kernel=kernel)
        if seconds is not None:
            m.histogram(
                "specpride_dispatch_seconds",
                "dispatch-call wall time (async: excludes device execution "
                "unless sync_timing)", labels=("kernel",),
            ).observe(seconds, kernel=kernel)
        pack_labels = (
            {"real_elems": int(real_elems), "padded_elems": int(padded_elems)}
            if real_elems is not None and padded_elems else {}
        )
        self.journal.emit(
            "dispatch", kernel=kernel, rows=rows, padded_rows=padded_rows,
            **pack_labels,
        )
        if seconds is not None and t_start is not None:
            tracing.current().complete(
                f"kernel:{kernel}", t_start, seconds,
                kernel=kernel, shape_key=list(shape_key), rows=rows,
                padded_rows=padded_rows, compile=is_new_shape,
                **pack_labels,
            )

    def _note_d2h(self, arrays) -> None:
        self.metrics.counter(
            "specpride_bytes_d2h_total", "bytes fetched device->host",
        ).inc(sum(int(a.nbytes) for a in arrays))
        self._note_device_memory()

    def _note_device_memory(self) -> None:
        """Device memory high-water gauge (best effort: CPU/older PJRT
        backends expose no memory_stats)."""
        try:
            if self.device is not None:
                stats = self.device.memory_stats()
            else:
                import jax

                stats = jax.local_devices()[0].memory_stats()
        except Exception:
            return
        if not stats:
            return
        peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
        if peak:
            g = self.metrics.gauge(
                "specpride_device_peak_bytes_in_use",
                "high-water device memory (bytes) observed at collect time",
            )
            g.set(max(float(peak), g.value()))

    def _dispatch_size(self, chunk: int, b: int) -> int:
        """Dispatch (padded) cluster count: the chunk size rounded up to a
        power of two (so odd-sized tail batches reuse compiled shapes), then
        to a multiple of the mesh size when sharding.

        The 64-row floor amortizes compile shapes, but it must never
        overshoot the memory-derived ``chunk``: with very wide rows (e.g.
        medoid k*m ~ 2^24) chunk can be 1-4, and a hard floor of 64 would
        exceed the ``max_grid_elements`` budget up to 64x (device OOM
        risk).  Clamping the floor to pow2(chunk) bounds padding at 2x the
        budget."""
        size = _pow2(min(chunk, b), floor=min(64, _pow2(chunk)))
        if self.mesh is not None:
            n = self.mesh.size
            size = ((size + n - 1) // n) * n
        return size

    def _ship(self, *arrays: np.ndarray):
        """Shard inputs over the mesh (if any) along the cluster axis.

        Mesh-less, the host arrays are returned as-is and jit transfers
        them implicitly — still a real H2D, so both paths count bytes."""
        self.metrics.counter(
            "specpride_bytes_h2d_total", "bytes shipped host->device",
        ).inc(sum(int(a.nbytes) for a in arrays))
        if self.mesh is None:
            return arrays
        from specpride_tpu.parallel.mesh import shard_batch_arrays

        return shard_batch_arrays(self.mesh, *arrays)

    def _put_batch(self, arrays: list[np.ndarray]) -> list:
        """One batched host->device transfer for a kernel's argument list.

        ``jax.device_put`` on a pytree ships every leaf in a single
        round trip — per-array puts each pay ~70 ms of tunnel latency on
        remote-device hosts (measured: 16 arrays 0.38 s separate vs
        0.056 s batched)."""
        import jax

        self.metrics.counter(
            "specpride_bytes_h2d_total", "bytes shipped host->device",
        ).inc(sum(int(a.nbytes) for a in arrays))
        return jax.device_put(arrays)

    def _timed_batches(self, batches):
        """Iterate pack output under the "pack" phase timer (pack functions
        may be lists or generators; either way the host work lands here)."""
        it = iter(batches)
        while True:
            with self.stats.phase("pack"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
            yield batch

    def _collect(self, arrays):
        """Fetch all pending device results to host.  Every blocking read
        pays a full tunnel round trip (~0.1 s measured) and the D2H link is
        the pipeline bottleneck (~25 MB/s vs ~1.4 GB/s H2D), so ALL copies
        start asynchronously before the first blocking read — transfers
        overlap each other and the still-running kernels."""
        if self.sync_timing:
            with self.stats.phase("device"):
                for a in arrays:
                    if hasattr(a, "block_until_ready"):
                        a.block_until_ready()
        with self.stats.phase("d2h"):
            faults.check("d2h")
            for a in arrays:
                if hasattr(a, "copy_to_host_async"):
                    a.copy_to_host_async()
            out = [np.asarray(a) for a in arrays]
        self._note_d2h(out)
        return out

    # -- two-phase chunk protocol (pipelined CLI executor) ---------------

    def prepare_chunk(
        self, method: str, clusters: list[Cluster], config,
        cos_config=None, stats: RunStats | None = None,
    ) -> PreparedChunk | None:
        """Phase 1: build every host-side packed input ``method`` needs,
        off the dispatch thread.

        The pipelined executor calls this from its background packer
        thread — and, with ``--pack-workers N``, from N POOL workers
        CONCURRENTLY on distinct chunks — each with a PRIVATE per-chunk
        ``stats`` (merged into the run's stats at handoff, so packer time
        is attributed to the ``pack`` phase instead of being swallowed
        into the consumer's ``compute`` wall time).  Only pure host work
        happens here — tables, flat packs, cosine member prep — never a
        device dispatch or a mutation of backend state, which is what
        makes concurrent calls safe: chunks share nothing mutable (the
        bucket-plan cache and the native-library loaders are
        lock-protected; ``seg_argsort`` and the C++ kernels take only
        their arguments).

        Returns ``None`` when the method/path has no pack stage worth
        splitting: mesh and bucketized layouts interleave packing with
        per-bucket dispatch, best-spectrum is a trivial join, and the
        device medoid path packs per bucket.  Callers then fall back to
        the one-shot ``run_*`` entry points (the executor still wins by
        materializing the chunk's clusters ahead of time)."""
        if not self.supports_prepare(method) or not clusters:
            return None
        faults.check("prepare")
        st = stats if stats is not None else self.stats
        if method == "bin-mean":
            return self._prepare_bin_mean(clusters, config, cos_config, st)
        if method == "gap-average":
            return self._prepare_gap_average(clusters, config, st)
        if method == "medoid":
            return self._prepare_medoid(clusters, config, st)
        return None

    def supports_prepare(self, method: str) -> bool:
        """True when ``prepare_chunk`` has a real pack stage for ``method``
        on this backend's configuration — the pipelined executor uses this
        to decide whether forcing chunked execution buys any overlap.
        Must mirror the serial path selection exactly: medoid is prepared
        only on the layout="auto" native path, because layouts that force
        the device kernel must keep using it under prefetch (identical
        outputs at every depth is the executor's contract)."""
        if self.mesh is not None or self.layout == "bucketized":
            return False
        if method == "bin-mean":
            return True
        if self.precision != "f32":
            # reduced precision routes gap-average and medoid onto their
            # bucketized device paths (which pack per bucket, one-shot);
            # only bin-mean's flat path keeps a separable pack stage
            return False
        if method == "gap-average":
            return True
        if method == "medoid":
            from specpride_tpu.ops import medoid_native

            return self.layout == "auto" and medoid_native.available()
        return False

    def run_prepared(
        self, prepared: PreparedChunk
    ) -> tuple[list[Spectrum], np.ndarray | None]:
        """Phase 2: dispatch + finalize a ``prepare_chunk`` product on the
        caller's (dispatch) thread.  Returns ``(representatives,
        cosines-or-None)`` — cosines only for the fused bin-mean + QC
        path, mirroring ``run_bin_mean_with_cosines``.

        Opens the SAME ``method:*`` span the one-shot ``run_*`` entry
        points are decorated with (oracle and device traces must diff
        cleanly whether or not a run was pipelined); under prefetch the
        span covers the compute stage only — pack time lives in the
        packer lane's ``pipeline:pack`` spans."""
        faults.check("dispatch")
        if prepared.method == "bin-mean":
            name = (
                "method:bin_mean_with_cosines"
                if prepared.cos_config is not None else "method:bin_mean"
            )
            with tracing.span(name, backend="tpu", prepared=True):
                return self._finish_bin_mean(prepared)
        if prepared.method == "gap-average":
            with tracing.span(
                "method:gap_average", backend="tpu", prepared=True
            ):
                return self._finish_gap_average(prepared), None
        if prepared.method == "medoid":
            with tracing.span(
                "method:medoid", backend="tpu", prepared=True
            ):
                indices = self._finish_medoid_indices(prepared)
                return (
                    [
                        c.members[i]
                        for c, i in zip(prepared.clusters, indices)
                    ],
                    None,
                )
        raise ValueError(prepared.method)

    # -- binned-mean consensus (K1) -------------------------------------

    # method-level spans share names with the numpy oracle's (labeled
    # backend="tpu" vs "numpy") so oracle and device traces diff cleanly

    @tracing.traced("method:bin_mean", backend="tpu")
    def run_bin_mean(
        self, clusters: list[Cluster], config: BinMeanConfig = BinMeanConfig()
    ) -> list[Spectrum]:
        """Batched equivalent of ref src/binning.py:291-297 on the packed
        ragged layout; dispatches all chunks asynchronously, then collects
        (overlapping H2D/compute/D2H).

        Single-device runs use the zero-padding FLAT layout (H2D bytes are
        the bottleneck on tunneled hosts; bucket padding wastes ~50% of
        them).  With a mesh, the (B, K) bucket layout shards along the
        cluster axis — a flat peak axis would split clusters across
        devices."""
        from specpride_tpu.data.packed import pack_bucketize_bin_mean

        faults.check("dispatch")
        if self.mesh is None and self.layout != "bucketized":
            # host ("auto") / flat-device paths; validation happens in the
            # shared pack stage (_prepare_bin_mean)
            return self._finish_bin_mean(
                self._prepare_bin_mean(clusters, config, None, self.stats)
            )[0]

        _check_no_empty(clusters)
        for c in clusters:
            numpy_backend.check_uniform_charge(c.members)

        from specpride_tpu.ops import binning

        kfn = self._kfn(
            binning.bin_mean_deduped_compact,
            binning.bin_mean_deduped_compact_donated,
        )
        out: list[Spectrum | None] = [None] * len(clusters)
        pending = []
        st = self.stats
        for batch in self._timed_batches(
            pack_bucketize_bin_mean(clusters, config, self.batch_config)
        ):
            b, k = batch.mz.shape
            with st.phase("pack"):
                enc_mz, enc_int, scale, tokens = self._encode_bucketized(
                    "bin-mean", batch.mz, batch.intensity
                )
            chunk = max(1, self.max_grid_elements // max(k * 4, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                # exact total surviving-bin bound for this chunk -> the
                # compacted D2H buffer carries only real output bytes
                with st.phase("pack"):
                    dist = quantize.distinct_bins_per_row(
                        batch.bins[lo:hi], config.n_bins
                    )
                    # pow2: cap is a static jit arg — see _pow2
                    cap = _cap_class(int(dist.sum()), floor=1024)
                lcap = _pow2(int(batch.n_members.max(initial=1)))
                with st.phase("dispatch"):
                    t0 = time.perf_counter()
                    fused = kfn(
                        *self._ship(
                            _pad_axis0(enc_mz[lo:hi], size),
                            _pad_axis0(enc_int[lo:hi], size),
                            # pad phantom rows with the sentinel so they emit
                            # no output bins
                            _pad_axis0(
                                batch.bins[lo:hi], size, fill=config.n_bins
                            ),
                            _pad_axis0(batch.n_members[lo:hi], size),
                        ),
                        config=config,
                        total_cap=cap,
                        # dedup bounds (row, bin) runs at the member count
                        lcap=lcap,
                    )
                    # timed INSIDE the phase block so the kernel span's
                    # end precedes the dispatch span's — time-containment
                    # nesting (aggregate_spans, Perfetto) depends on it
                    dt = time.perf_counter() - t0
                self._note_dispatch(
                    "bin_mean_bucketized", (size, k, cap, lcap, *tokens),
                    rows=hi - lo, padded_rows=size,
                    real_elems=lambda lo=lo, hi=hi: (
                        batch.bins[lo:hi] != config.n_bins
                    ).sum(),
                    padded_elems=size * k,
                    seconds=dt, t_start=t0,
                )
                pending.append((batch, lo, hi, cap, scale, fused))

        fuseds = self._collect([p[-1] for p in pending])
        with st.phase("finalize"):
            self._finalize_bin_mean(pending, fuseds, clusters, out)
        return [s for s in out if s is not None]

    def _encode_bucketized(self, method: str, mz, intensity, **note):
        """Precision-encode one (B, K) bucketized batch's m/z + intensity
        channels: ``(enc_mz, enc_int, scale, shape_tokens)``.  f32 is an
        identity with no tokens, so f32 shape classes (and therefore the
        jit caches and shape manifests) are byte-identical to pre-
        precision runs."""
        if self.precision == "f32":
            return mz, intensity, None, ()
        enc_mz, mz_tok = quantize.encode_mz(mz, self.precision)
        enc_int, scale = quantize.encode_intensity_rows(
            intensity, self.precision
        )
        self._note_precision(
            method, mz=mz_tok, intensity=self.precision, **note
        )
        return enc_mz, enc_int, scale, (self.precision, mz_tok)

    def _finalize_bin_mean(self, pending, fuseds, clusters, out) -> None:
        for (batch, lo, hi, cap, scale, _), fused in zip(pending, fuseds):
            for ci, r_mz, r_int in _iter_compacted(fused, cap, hi - lo):
                gi = batch.source_indices[lo + ci]
                members = clusters[gi].members
                if scale is not None:
                    # int8 codes were averaged on device; rescale the
                    # means by the cluster's pack-time scale (linear)
                    r_int = r_int * float(scale[lo + ci])
                out[gi] = Spectrum(
                    mz=r_mz,
                    intensity=r_int,
                    # exact f64 mean, as the oracle (ref src/binning.py:224)
                    precursor_mz=float(
                        np.mean([s.precursor_mz for s in members])
                    ),
                    precursor_charge=members[0].precursor_charge,
                    title=batch.cluster_ids[lo + ci],
                )

    def _prepare_bin_mean(
        self, clusters: list[Cluster], config: BinMeanConfig,
        cos_config, st: RunStats, member_prep: bool = True,
    ) -> PreparedChunk:
        """Pack stage shared by the host ("auto") and flat-device K1
        paths: input validation, the flat zero-padding pack, and — when a
        fused QC is requested — the representative-independent half of
        the cosine prep.  Under the pipelined executor all of this runs
        on the packer thread.  ``member_prep=False`` defers the flat
        member-cosine prep to ``_finish_bin_mean`` — serial callers pass
        it so that prep keeps overlapping the in-flight D2H stream as it
        did before the split (the pipelined executor preps eagerly
        instead, overlapping the previous chunk's dispatch)."""
        from specpride_tpu.data.packed import _as_table, pack_flat_bin_mean

        _check_no_empty(clusters)
        for c in clusters:
            numpy_backend.check_uniform_charge(c.members)
        kind = "bin_mean_host" if self.layout == "auto" else "bin_mean_flat"
        if self.precision != "f32":
            # reduced precision is a DEVICE-bytes feature: the host path
            # ships nothing to shrink, so a non-f32 run opts bin-mean
            # onto the flat device path (journaled once via routing)
            if kind == "bin_mean_host":
                self._note_routing(
                    "bin-mean", "xla", "precision-requested", "precision"
                )
            kind = "bin_mean_flat"
        native = False
        if kind == "bin_mean_host" and cos_config is not None:
            from specpride_tpu.ops import cosine_native

            native = cosine_native.available()
        data: dict = {}
        with st.phase("pack"):
            table = _as_table(clusters)
            data["batches"] = pack_flat_bin_mean(
                table, config, max_elements=self.max_grid_elements // 4,
                precision=self.precision,
            )
            if cos_config is not None:
                if native:
                    data["mprep"] = self._prep_cosine_native(
                        table, cos_config
                    )
                elif member_prep:
                    # host consensus without the C++ cosine, or the flat
                    # device layout: the device flat cosine path's member
                    # half (rep half needs the representatives)
                    data["mprep_flat"] = self._prep_cosine_members(
                        clusters, cos_config
                    )
        return PreparedChunk(
            "bin-mean", kind, clusters, config, cos_config, data
        )

    def _finish_bin_mean(
        self, prepared: PreparedChunk
    ) -> tuple[list[Spectrum], np.ndarray | None]:
        """Compute stage for ``_prepare_bin_mean`` output: host run
        reductions (+ interleaved native QC cosines) on the "auto"
        layout, device dispatch + async D2H on the flat layout."""
        clusters, config = prepared.clusters, prepared.config
        ccfg = prepared.cos_config
        batches = prepared.data["batches"]
        st = self.stats
        if prepared.kind == "bin_mean_flat":
            pending = self._dispatch_flat_batches(
                batches, config, staged=prepared.data.pop("staged", None)
            )
            mprep_flat = prepared.data.get("mprep_flat")
            if ccfg is not None and mprep_flat is None:
                # deferred (serial) member prep: runs while the bin-mean
                # kernel and its async D2H stream are in flight
                with st.phase("pack"):
                    mprep_flat = self._prep_cosine_members(clusters, ccfg)
            reps = self._bin_mean_flat_finish(pending, clusters)
            if ccfg is None:
                return reps, None
            return reps, self._cosines_from_member_prep(
                reps, mprep_flat, ccfg
            )
        # host path: per-chunk host run reductions; the native C++ cosine
        # interleaves per batch so the working set stays in cache (the
        # measured mesh-less winner — see run_gap_average for the link
        # economics that make host reductions beat device round trips)
        out: list[Spectrum | None] = [None] * len(clusters)
        mprep = prepared.data.get("mprep")
        cosines = (
            np.zeros(len(clusters), dtype=np.float64)
            if mprep is not None else None
        )
        for batch in batches:
            self._host_bin_mean_chunk(batch, config, clusters, out)
            if mprep is not None:
                lo = batch.source_indices[0]
                hi = batch.source_indices[-1] + 1
                with st.phase("compute"):
                    cosines[lo:hi] = self._cosine_native_rows(
                        out[lo:hi], mprep, ccfg, lo, hi
                    )
        st.count("clusters", len(clusters))
        reps = [s for s in out if s is not None]
        if ccfg is not None and mprep is None:
            # no C++ cosine built: device flat cosine over the host reps
            mprep_flat = prepared.data.get("mprep_flat")
            if mprep_flat is None:  # deferred by a serial caller
                with st.phase("pack"):
                    mprep_flat = self._prep_cosine_members(clusters, ccfg)
            cosines = self._cosines_from_member_prep(
                reps, mprep_flat, ccfg
            )
        return reps, cosines

    def _cosines_from_member_prep(
        self, reps: list[Spectrum], mprep_flat: dict, ccfg: CosineConfig
    ) -> np.ndarray:
        """Finish the flat device cosine from a prepacked member half."""
        with self.stats.phase("pack"):
            prep = self._prep_cosine_reps(reps, mprep_flat, ccfg)
        return self._dispatch_cosine_flat(prep)

    def _flat_chunk_host_args(self, batch, config: BinMeanConfig):
        """Host half of one flat chunk dispatch: the run pass (counts,
        oracle-exact quorum, m/z means), the padded device argument list
        — precision-encoded when the batch was packed reduced — and the
        dispatch metadata.  Split from the kernel call so the executor's
        double-buffered H2D lane (``stage_chunk``) can transfer chunk
        i+1's arguments while chunk i dispatches.

        Input padding uses the half-octave classes like the output caps:
        the measured tunneled H2D link (~90 MB/s with multi-second jitter,
        round-5 profile) makes input bytes the pipeline's largest single
        cost — worth one extra XLA compile class per octave."""
        sent = np.int32(2**31 - 1)
        g = batch.gbin
        n = g.size
        n_pad = _cap_class(n, floor=1024)
        rows = len(batch.source_indices)
        cap = _cap_class(batch.n_distinct_total, floor=1024)
        rcap = _cap_class(batch.n_distinct_total + 1, floor=1024)
        # dedup bounds every (row, bin) run at the row's member count
        lcap = _pow2(int(batch.n_members.max(initial=1)))

        # host run pass over the sorted composite (run structure carried
        # from the packer) — everything except the heavy intensity
        # reduction, which is the device's job; m/z never crosses the link
        aux = self._host_run_pass(batch, config)
        keep_runs = np.zeros(rcap, dtype=bool)
        keep_runs[: aux["keep"].size] = aux["keep"]

        prec = (
            batch.precision
            if getattr(batch, "codes", None) is not None else "f32"
        )
        if prec != "f32":
            # reduced path: the int32 gbin channel collapses to a 1-byte
            # run-start mask (the kernel only needs boundaries), and
            # intensity ships as the packer's bf16/int8 codes — the
            # first padding slot starts the tail run keep_runs drops
            run_start = np.zeros(n_pad, dtype=bool)
            run_start[batch.run_starts] = True
            if n < n_pad:
                run_start[n] = True
            if n_pad:
                run_start[0] = True
            codes = np.zeros(n_pad, dtype=batch.codes.dtype)
            codes[:n] = batch.codes
            args = [codes, run_start, keep_runs]
            kernel = "bin_mean_flat_q"
            shape_key = (n_pad, cap, rcap, lcap, prec)
            self._note_precision(
                "bin-mean", layout="flat", intensity=prec,
                gbin="run_mask",
            )
        else:
            args = [
                np.pad(batch.intensity, (0, n_pad - n)),
                np.pad(g, (0, n_pad - n), constant_values=sent),
                keep_runs,
            ]
            kernel = "bin_mean_flat_intensity"
            shape_key = (n_pad, cap, rcap, lcap)
        meta = dict(
            kernel=kernel, shape_key=shape_key, n=n, n_pad=n_pad,
            rows=rows, cap=cap, rcap=rcap, lcap=lcap, precision=prec,
        )
        return args, aux, meta

    def _flat_chunk_dispatch(
        self, batch, config: BinMeanConfig, staged=None
    ):
        """One flat chunk: host args (or the H2D lane's pre-staged device
        arrays) + the intensity kernel call.  Returns ``(device_array,
        aux)`` where ``aux`` carries the host-computed ``kept_mz`` /
        ``row_out_offsets`` / ``rows`` that ``_emit_bin_mean_rows``
        assembles with the device means.  Shared by the serial flat path
        and the pipelined native path so the protocol lives once."""
        from specpride_tpu.ops import binning

        impl = self._impl_for("bin-mean")
        if staged is not None:
            dev_args, aux, meta = staged
        else:
            args, aux, meta = self._flat_chunk_host_args(batch, config)
            dev_args = self._put_batch(args)
        if meta["precision"] != "f32":
            fn = self._kfn(
                binning.bin_mean_flat_q, binning.bin_mean_flat_q_donated
            )
        else:
            fn = self._kfn(
                binning.bin_mean_flat_intensity,
                binning.bin_mean_flat_intensity_donated,
            )
        t0 = time.perf_counter()
        fused = fn(
            *dev_args,
            total_cap=meta["cap"],
            rcap=meta["rcap"],
            lcap=meta["lcap"],
            impl=impl,
        )
        self._note_dispatch(
            meta["kernel"] if impl == "scan"
            else meta["kernel"] + "_pallas",
            meta["shape_key"],
            rows=meta["rows"], padded_rows=meta["rows"],
            real_elems=meta["n"], padded_elems=meta["n_pad"],
            seconds=time.perf_counter() - t0, t_start=t0,
        )
        return fused, aux

    # -- double-buffered H2D staging (--h2d-buffer) ----------------------

    def supports_h2d_stage(self, prepared) -> bool:
        """True when ``stage_chunk`` can pre-transfer this prepared
        chunk's device inputs ahead of dispatch.  Only the flat bin-mean
        device path stages today: the host paths ship nothing, and the
        bucketized/mesh layouts interleave packing with per-bucket
        dispatch (their puts already overlap the previous bucket's
        kernel)."""
        return (
            prepared is not None
            and getattr(prepared, "kind", None) == "bin_mean_flat"
        )

    def stage_chunk(self, prepared: "PreparedChunk") -> int:
        """Double-buffered H2D: transfer a prepared chunk's device
        arguments NOW, on the executor's transfer lane, so the dispatch
        lane finds them resident (``pipeline:h2d`` spans wrap the lane's
        calls).  Returns bytes staged.  The staged device arrays are
        consumed exactly once by ``_dispatch_flat_batches`` — a retry
        after a mid-chunk error re-puts from the host numpy the prepared
        chunk still holds, so donation can never see a buffer twice."""
        staged = []
        total = 0
        for batch in prepared.data["batches"]:
            args, aux, meta = self._flat_chunk_host_args(
                batch, prepared.config
            )
            total += sum(int(a.nbytes) for a in args)
            staged.append((self._put_batch(args), aux, meta))
        prepared.data["staged"] = staged
        return total

    def _host_run_pass(self, batch, config: BinMeanConfig) -> dict:
        """Per-run host pass over one flat chunk's sorted composite:
        counts, the ORACLE-EXACT int quorum (int(n*frac)+1, ref
        src/binning.py:183), per-bin m/z means (f32 reduceat — the
        oracle's accumulation order: the stable (row, bin) sort keeps
        member order within a bin), and per-row output extents.  Shared
        by the device flat path (which ships the keep mask) and the full
        host path (which adds one intensity reduceat)."""
        g = batch.gbin
        n = g.size
        rows = len(batch.source_indices)
        starts_idx = batch.run_starts
        counts = np.diff(np.append(starts_idx, n))
        mz_sums = (
            np.add.reduceat(batch.mz, starts_idx)
            if starts_idx.size
            else np.zeros(0, np.float32)
        )
        row_of_run = g[starts_idx].astype(np.int64) // np.int64(
            config.n_bins + 1
        )
        if config.apply_peak_quorum:
            quorum = (
                batch.n_members[row_of_run].astype(np.float64)
                * config.quorum_fraction
            ).astype(np.int64) + 1
        else:
            quorum = np.ones_like(counts)
        keep = counts >= quorum
        # oracle dtype chain: f32 sum promoted to f64 by the int division
        kept_mz = (mz_sums.astype(np.float64) / counts)[keep]
        n_out = np.bincount(row_of_run[keep], minlength=rows)
        row_out_offsets = np.zeros(rows + 1, dtype=np.int64)
        np.cumsum(n_out, out=row_out_offsets[1:])
        return dict(
            kept_mz=kept_mz, row_out_offsets=row_out_offsets, rows=rows,
            keep=keep, counts=counts, starts_idx=starts_idx,
        )

    def _host_bin_mean_chunk(self, batch, config, clusters, out) -> None:
        """One flat chunk fully on the host: run pass + ONE intensity
        reduceat, emitted straight into ``out``."""
        st = self.stats
        with st.phase("compute"):
            aux = self._host_run_pass(batch, config)
            int_sums = (
                np.add.reduceat(batch.intensity, aux["starts_idx"])
                if aux["starts_idx"].size
                else np.zeros(0, np.float32)
            )
            kept_int = (
                int_sums.astype(np.float64) / aux["counts"]
            )[aux["keep"]]
        with st.phase("finalize"):
            self._emit_bin_mean_rows(batch, kept_int, aux, clusters, out)

    # NOTE on the host K1 economics (mesh-less ``layout="auto"``, the
    # measured choice — round-5 profile): after the packer's sorted pass
    # the per-run host work already includes counts, quorum and m/z means;
    # the only remaining reduction is ONE intensity reduceat (~20 ms for
    # 2.8M peaks), ~20x cheaper than shipping ~25 MB over the tunneled
    # link for the device to do it.  The device flat path stays selectable
    # (``layout="flat"``) and the bucketized path carries mesh runs, where
    # sharding changes the economics.  Both now route through
    # ``_prepare_bin_mean`` / ``_finish_bin_mean``.

    def _dispatch_flat_batches(
        self, batches, config: BinMeanConfig, staged=None
    ):
        """Dispatch prepacked flat chunks asynchronously and start their
        D2H copies; returns the pending list for
        ``_bin_mean_flat_finish``.  ``staged`` (from ``stage_chunk``) is
        consumed positionally and exactly once — ownership transfers
        here, so an error mid-list leaves nothing half-donated for a
        retry to trip over."""
        pending = []
        st = self.stats
        for i, batch in enumerate(batches):
            with st.phase("dispatch"):
                fused, aux = self._flat_chunk_dispatch(
                    batch, config,
                    staged=(
                        staged[i]
                        if staged is not None and i < len(staged)
                        else None
                    ),
                )
            # fetch in a background thread now — on the slow device->host
            # link the copy is the critical path, and the caller has host
            # work (the fused pipeline's cosine prep; the next chunk's
            # np.pad) to hide it behind.  Under sync_timing keep the raw
            # device array so _collect can still split device vs d2h time.
            pending.append((
                batch, aux,
                fused if self.sync_timing else _AsyncFetch(fused),
            ))
        return pending

    def _bin_mean_flat_finish(self, pending, clusters) -> list[Spectrum]:
        out: list[Spectrum | None] = [None] * len(clusters)
        st = self.stats
        if self.sync_timing:
            fuseds = self._collect([p[-1] for p in pending])
        else:
            with st.phase("d2h"):
                fuseds = [p[-1].get() for p in pending]
            self._note_d2h(fuseds)
        with st.phase("finalize"):
            for (batch, aux, _), fused in zip(pending, fuseds):
                self._emit_bin_mean_rows(batch, fused, aux, clusters, out)
        return [s for s in out if s is not None]

    # -- gap-average consensus (K3) -------------------------------------

    @tracing.traced("method:gap_average", backend="tpu")
    def run_gap_average(
        self,
        clusters: list[Cluster],
        config: GapAverageConfig = GapAverageConfig(),
    ) -> list[Spectrum]:
        """Batched equivalent of ref src/average_spectrum_clustering.py:158-164.

        MESH-LESS runs use a fully vectorized HOST path by design, not as a
        fallback: gap-average is a memory-bound group-by whose grouping
        (sort + f64 gap detection) must run on the host anyway for float64
        parity, leaving the device only segment means — and the measured
        single-chip reality (round-3 bench, v5e behind a tunneled link) is
        that shipping ~50 MB of peaks to compute means costs 14x more than
        computing them in the same host pass (device 755 clusters/s vs
        10,476 oracle).  The vectorized host path instead beats the
        per-cluster oracle severalfold with bit-identical f64 semantics
        (one global lexsort + reduceat — ``data.packed.gap_global_segments``
        shared with the device packer).  With a mesh, the (B, K) bucketized
        device path shards the segment reductions across devices
        (``ops.gap_average``), where interconnect bandwidth changes the
        trade-off.

        Routing: when --mesh/--layout ask for the bucketized device path,
        the per-(method, platform) routing table (``warmstart.routing``)
        decides which core carries it — the vectorized host consensus
        (the measured winner on CPU-only jax: the device kernel ran at
        0.29x of it, BENCH_r08), the XLA seg-scan kernel, or the fused
        Pallas segment-mean kernel — and the decision is journaled,
        unless ``force_device`` pins the requested device kernels."""
        faults.check("dispatch")
        if self.precision != "f32":
            # reduced precision is a device-bytes feature: the host path
            # ships nothing to shrink, so a non-f32 run opts gap-average
            # onto the bucketized device path (journaled once)
            if self.mesh is None and self.layout != "bucketized":
                self._note_routing(
                    "gap-average", "xla", "precision-requested",
                    "precision",
                )
            return self._run_gap_average_mesh(clusters, config)
        if self.mesh is None and self.layout != "bucketized":
            return self._run_gap_average_host(clusters, config)
        if not self.force_device:
            d = self.routing.decide("gap-average", self._platform())
            if d.path == "host-vectorized":
                self._note_routing(
                    "gap-average", d.path, d.reason, d.source
                )
                return self._run_gap_average_host(clusters, config)
        return self._run_gap_average_mesh(clusters, config)

    def _platform(self) -> str:
        """Routing-table platform key: "cpu" when every visible device is
        a CPU, else the default jax backend name (tpu/gpu/...)."""
        if _cpu_only_devices():
            return "cpu"
        import jax

        try:
            return jax.default_backend()
        except Exception:  # bring-up failure: route like a cpu host
            return "cpu"

    def _impl_for(self, method: str, pallas_capable: bool = True) -> str:
        """Segmented-reduction core for ``method``'s device kernels:
        "scan" (the XLA Hillis-Steele chain) or "pallas" (the fused
        ``seg_mean_pallas`` single pass), per the routing table.  The
        trivial "xla" default stays unjournaled; every decision the
        backend CANNOT honor at this point — a Pallas promotion where
        lowering (or a Pallas variant of the kernel) is unavailable, a
        host-vectorized entry reaching a dispatch site whose
        host-vs-device choice was already made by layout — is journaled
        as the xla fallback, so an override never appears accepted
        while silently changing nothing."""
        d = self.routing.decide(method, self._platform())
        if d.path == "host-vectorized":
            # under --force-device the operator explicitly pinned the
            # device kernels — the host route is knowingly overridden
            # and stays event-silent (the documented pin contract).
            # Otherwise this is an override reaching a dispatch site
            # whose host-vs-device choice was already made by layout:
            # journal the fallback so it never looks accepted.
            if not self.force_device:
                self._note_routing(
                    method, "xla", "host-path-not-available-here",
                    d.source,
                )
            return "scan"
        if d.path != "pallas":
            return "scan"
        if not pallas_capable:
            self._note_routing(
                method, "xla", "no-pallas-variant-for-kernel", d.source
            )
            return "scan"
        from specpride_tpu.ops import pallas_kernels as pk

        if pk.has_pallas():
            self._note_routing(method, "pallas", d.reason, d.source)
            return "pallas"
        self._note_routing(method, "xla", "pallas-unavailable", d.source)
        return "scan"

    def _note_routing(
        self, method: str, path: str, reason: str, source: str = "static"
    ) -> None:
        """Journal/log a device-routing decision ONCE per backend — the
        operator must be able to see why a requested layout was not
        executed, without one event per chunk."""
        key = (method, path)
        if key in self._routing_noted:
            return
        self._routing_noted.add(key)
        logger.info(
            "routing %s to the %s path (%s, %s; --force-device overrides)",
            method, path, reason, source,
        )
        self.journal.emit(
            "routing", method=method, path=path, reason=reason,
            source=source,
        )

    def _run_gap_average_host(
        self, clusters: list[Cluster], config: GapAverageConfig
    ) -> list[Spectrum]:
        """Exact-f64 host consensus (see ``run_gap_average``): the
        multithreaded C++ grouping when built (``ops.gap_native``), else
        one vectorized numpy pass — split into ``_prepare_gap_average``
        (pack: table build + gathers / global segmentation) and
        ``_finish_gap_average`` (grouping + finalize) so the pipelined
        executor can pack ahead on its background thread.

        Measured bound (round 5): the bench host exposed ONE cpu core
        (``os.sched_getaffinity``), so the C++ path's modest ~1.3x over
        the oracle was the single-core ceiling — its win is allocation
        avoidance and cache locality, and the thread pool only pays off
        on multi-core hosts.  The remaining per-run cost splits roughly
        pack 0.10s (columnar table build + gathers) / compute 0.075s
        (C++ sort+group) / finalize 0.04s (Spectrum assembly) for 2000
        clusters — no single component dominates, which is exactly why
        overlapping pack with compute across chunks pays."""
        return self._finish_gap_average(
            self._prepare_gap_average(clusters, config, self.stats)
        )

    def _prepare_gap_average(
        self, clusters: list[Cluster], config: GapAverageConfig,
        st: RunStats,
    ) -> PreparedChunk:
        """Pack stage of the host gap-average paths: the columnar table
        plus either the native path's ordered-peak views or the full
        vectorized f64 segmentation."""
        from specpride_tpu.data.packed import _as_table, gap_global_segments
        from specpride_tpu.ops import gap_native

        _check_no_empty(clusters)
        data: dict = {}
        with st.phase("pack"):
            table = _as_table(clusters)
            idx = table.cluster_order()
            if gap_native.available():
                kind = "gap_native"
                # member-concatenation order per cluster (the oracle's
                # input to its stable sort); zero-copy when contiguous
                mz_c, int_c, _ = self._cluster_ordered_peaks(table, idx)
                offs = np.zeros(table.n_clusters + 1, dtype=np.int64)
                np.cumsum(idx.total_peaks, out=offs[1:])
                data.update(idx=idx, mz_c=mz_c, int_c=int_c, offs=offs)
            else:
                kind = "gap_vector"
                g = gap_global_segments(table, idx, config)
                data.update(
                    idx=idx, g=g, s_int=table.intensity[g["order"]]
                )
        return PreparedChunk("gap-average", kind, clusters, config, None, data)

    def _finish_gap_average(
        self, prepared: PreparedChunk
    ) -> list[Spectrum]:
        clusters, config = prepared.clusters, prepared.config
        get_pepmass, get_rt = numpy_backend.resolve_gap_estimators(config)
        st = self.stats
        d = prepared.data
        idx = d["idx"]
        if prepared.kind == "gap_native":
            from specpride_tpu.ops import gap_native

            offs = d["offs"]
            with st.phase("compute"):
                out_mz, out_int, out_counts = gap_native.gap_average_groups(
                    d["mz_c"], d["int_c"], offs,
                    idx.n_members.astype(np.int64),
                    config.mz_accuracy,
                    config.tail_mode == "reference",
                    config.min_fraction, config.dyn_range,
                )
            out: list[Spectrum] = []
            with st.phase("finalize"):
                for ci, cluster in enumerate(clusters):
                    o0 = int(offs[ci])
                    k = int(out_counts[ci])
                    members = cluster.members
                    pep_mz, pep_z = get_pepmass(members)
                    out.append(
                        Spectrum(
                            # copies, not views: slices would pin the full
                            # peak-count-sized output buffers alive for
                            # the lifetime of every returned spectrum
                            mz=out_mz[o0 : o0 + k].copy(),
                            intensity=out_int[o0 : o0 + k].copy(),
                            precursor_mz=pep_mz,
                            precursor_charge=pep_z,
                            rt=get_rt(members),
                            title=cluster.cluster_id,
                        )
                    )
                st.count("clusters", len(clusters))
            return out

        g = d["g"]
        s_cluster, s_mz = g["s_cluster"], g["s_mz"]
        n_groups = g["n_groups"]
        s_int = d["s_int"]

        with st.phase("compute"):
            # per-group f64 sums over the globally sorted axis: group starts
            # are cluster starts plus gap positions
            group_start_mask = g["cluster_first_peak"] | g["gap"]
            gstarts = np.flatnonzero(group_start_mask)
            n_total_groups = gstarts.size
            if n_total_groups:
                sizes = np.diff(np.append(gstarts, s_mz.size))
                mz_sums = np.add.reduceat(s_mz, gstarts)
                int_sums = np.add.reduceat(s_int, gstarts)
            else:
                sizes = np.zeros(0, np.int64)
                mz_sums = int_sums = np.zeros(0, np.float64)
            gcluster = s_cluster[gstarts]
            nm = idx.n_members[gcluster].astype(np.float64)
            group_mz = mz_sums / sizes
            group_int = int_sums / nm
            # quorum (float compare, ref :74,80,85); singletons skip it
            # (ref :88-90 passes peaks straight to the dyn-range filter)
            quorum_ok = (nm == 1) | (sizes >= config.min_fraction * nm)
            # per-cluster dynamic-range floor over quorum-passing groups
            cluster_gstart = np.concatenate(
                [[0], np.cumsum(n_groups)[:-1]]
            ).astype(np.int64)
            if n_total_groups:
                masked = np.where(quorum_ok, group_int, -np.inf)
                # zero-group clusters (all members peakless) repeat a
                # neighbour's start; their kept_max is garbage but unused
                # (their keep slice is empty)
                rg = np.minimum(cluster_gstart, n_total_groups - 1)
                kept_max = np.maximum.reduceat(masked, rg)
                floor = kept_max / config.dyn_range
                keep = quorum_ok & (group_int >= floor[gcluster])
            else:
                keep = np.zeros(0, dtype=bool)

        out: list[Spectrum] = []
        with st.phase("finalize"):
            for ci, cluster in enumerate(clusters):
                g0 = cluster_gstart[ci]
                g1 = g0 + n_groups[ci]
                sel = keep[g0:g1]
                members = cluster.members
                pep_mz, pep_z = get_pepmass(members)
                out.append(
                    Spectrum(
                        mz=group_mz[g0:g1][sel],
                        intensity=group_int[g0:g1][sel],
                        precursor_mz=pep_mz,
                        precursor_charge=pep_z,
                        rt=get_rt(members),
                        title=cluster.cluster_id,
                    )
                )
            st.count("clusters", len(clusters))
        return out

    def _run_gap_average_mesh(
        self,
        clusters: list[Cluster],
        config: GapAverageConfig,
    ) -> list[Spectrum]:
        """Sharded (B, K) bucketized device path (see ``run_gap_average``)."""
        from specpride_tpu.data.packed import pack_bucketize_gap
        from specpride_tpu.ops import gap_average as ga

        _check_no_empty(clusters)
        get_pepmass, get_rt = numpy_backend.resolve_gap_estimators(config)
        impl = self._impl_for("gap-average")
        kname = (
            "gap_average_compact" if impl == "scan"
            else "gap_average_compact_pallas"
        )
        kfn = self._kfn(
            ga.gap_average_compact, ga.gap_average_compact_donated
        )

        out: list[Spectrum | None] = [None] * len(clusters)
        pending = []
        st = self.stats
        for batch in self._timed_batches(
            pack_bucketize_gap(clusters, config, self.batch_config)
        ):
            b, k = batch.mz.shape
            with st.phase("pack"):
                enc_mz, enc_int, scale, tokens = self._encode_bucketized(
                    "gap-average", batch.mz, batch.intensity
                )
                enc_seg = batch.seg
                if self.precision != "f32":
                    # segment ids are < K: exact int16 narrowing when the
                    # bucket fits (the kernel upcasts; token records it)
                    seg16 = quantize.narrow_i32_to_i16(
                        batch.seg, max_valid=k - 1
                    )
                    if seg16 is not None:
                        enc_seg = seg16
                    tokens = (*tokens, "i16" if seg16 is not None else "i32")
            chunk = max(1, self.max_grid_elements // max(k * 4, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                # exact total group-count bound for this chunk -> the
                # compacted D2H buffer carries only real output bytes
                # pow2: cap is a static jit arg — see _pow2
                cap = _cap_class(int(batch.n_groups[lo:hi].sum()), floor=1024)
                with st.phase("dispatch"):
                    t0 = time.perf_counter()
                    fused = kfn(
                        *self._ship(
                            _pad_axis0(enc_mz[lo:hi], size),
                            _pad_axis0(enc_int[lo:hi], size),
                            _pad_axis0(enc_seg[lo:hi], size),
                            _pad_axis0(batch.n_valid[lo:hi], size),
                            _pad_axis0(batch.quorum[lo:hi], size),
                            _pad_axis0(batch.n_members[lo:hi], size),
                        ),
                        config=config,
                        total_cap=cap,
                        impl=impl,
                    )
                    dt = time.perf_counter() - t0  # see bin_mean: span nesting
                self._note_dispatch(
                    kname, (size, k, cap, *tokens),
                    rows=hi - lo, padded_rows=size,
                    real_elems=lambda lo=lo, hi=hi: batch.n_valid[lo:hi].sum(),
                    padded_elems=size * k,
                    seconds=dt, t_start=t0,
                )
                pending.append((batch, lo, hi, cap, scale, fused))

        fuseds = self._collect([p[-1] for p in pending])
        with st.phase("finalize"):
            for (batch, lo, hi, cap, scale, _), fused in zip(
                pending, fuseds
            ):
                for ci, r_mz, r_int in _iter_compacted(fused, cap, hi - lo):
                    gi = batch.source_indices[lo + ci]
                    members = clusters[gi].members
                    pep_mz, pep_z = get_pepmass(members)
                    if scale is not None:
                        # int8 codes averaged on device; linear rescale
                        r_int = r_int * float(scale[lo + ci])
                    out[gi] = Spectrum(
                        mz=r_mz,
                        intensity=r_int,
                        precursor_mz=pep_mz,
                        precursor_charge=pep_z,
                        rt=get_rt(members),
                        title=batch.cluster_ids[lo + ci],
                    )
        return [s for s in out if s is not None]

    # -- medoid representative (K2) -------------------------------------

    def medoid_indices(
        self, clusters: list[Cluster], config: MedoidConfig = MedoidConfig()
    ) -> list[int]:
        """Per-cluster medoid member index (ref
        src/most_similar_representative.py:87-110 semantics): packed
        occupancy scatter + batched gram matmul on device; by default the
        winning index is ALSO selected on device (``medoid_device_select``)
        so D2H carries one int32 per cluster instead of (B, M, M) uint16
        count matrices — with the count fetch the transfer was the medoid
        path's largest cost on slow links.  ``medoid_device_select=False``
        restores the count fetch + exact float64 host finalize."""
        from specpride_tpu.data.packed import pack_bucketize
        from specpride_tpu.ops import similarity as sim
        from specpride_tpu.ops.similarity import medoid_finalize

        if (
            self.mesh is None and self.layout == "auto"
            and self.precision == "f32"
        ):
            from specpride_tpu.ops import medoid_native

            if medoid_native.available():
                # validation happens in _prepare_medoid (shared with the
                # pipelined prepare path) — no second scan here
                return self._medoid_indices_native(clusters, config)
        _check_no_empty(clusters)  # device path validates here
        # consult (and audit) the routing table: medoid has no Pallas
        # variant, so a pallas/host override journals its xla fallback
        # instead of being silently swallowed
        self._impl_for("medoid", pallas_capable=False)
        out: list[int] = [0] * len(clusters)
        pending = []
        st = self.stats
        for batch in self._timed_batches(
            pack_bucketize(clusters, self.batch_config, bucket_members=True)
        ):
            # shared-bin counts travel as uint16 (D2H is the bottleneck)
            if int(batch.n_peaks.max(initial=0)) >= 1 << 16:
                raise ValueError(
                    "medoid kernel: a member has >= 2**16 peaks; uint16 "
                    "shared-bin counts would overflow"
                )
            with st.phase("pack"):
                bins = quantize.medoid_bins_packed(batch, config)
                b, k = batch.mz.shape
                m = batch.m
                # host pre-sort by (bin, member) — the kernel does no device
                # sort; padding member maps to m, padding bin is the 2**30
                # sentinel, so padding sorts last either way
                mm = np.where(batch.member_id >= 0, batch.member_id, m).astype(
                    np.int64
                )
                key = bins.astype(np.int64) * (m + 1) + mm
                # rows are independent segments: threaded native sort
                from specpride_tpu.ops.segsort import seg_argsort

                b_rows, k = key.shape
                flat_order = seg_argsort(
                    key.reshape(-1),
                    np.arange(b_rows + 1, dtype=np.int64) * k,
                )
                order = flat_order.reshape(b_rows, k) - (
                    np.arange(b_rows, dtype=np.int64)[:, None] * k
                )
                sbins = np.take_along_axis(bins, order, axis=1)
                smm = np.take_along_axis(mm.astype(np.int32), order, axis=1)
                # OR-scan window: K always bounds a run, and the exact
                # bound costs several full host passes over (B, K) int64
                # to compute — a few extra device scan steps are cheaper
                lcap = _pow2(k)
                bin_fill = 2**30
                tokens: tuple = ()
                if self.precision != "f32":
                    # reduced packed path: the medoid ships only integer
                    # channels, so precision here is EXACT int16
                    # narrowing of the occupancy grid + member ids when
                    # the grid fits (outputs bit-identical to f32 runs);
                    # an oversized grid falls back to int32, journaled
                    real_max = int(
                        sbins[sbins < 2**30].max(initial=0)
                    )
                    b16 = quantize.narrow_i32_to_i16(sbins, real_max)
                    if b16 is not None and m < 2**15 - 1:
                        sbins = b16
                        smm = smm.astype(np.int16)
                        bin_fill = 2**15 - 1
                        tokens = ("i16",)
                        self._note_precision(
                            "medoid", bins="i16", member="i16",
                        )
                    else:
                        self._note_precision(
                            "medoid", bins="i32",
                            reason="grid-exceeds-int16",
                        )
            # largest device intermediate is the (K*M,) run×member
            # occupancy; allow it 4x the element budget (1 GB of f32 on a
            # 16 GB chip) — every extra chunk is a dispatch round-trip,
            # which the round-4 bench measured as the medoid's real cost
            chunk = max(1, (4 * self.max_grid_elements) // max(k * m, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                with st.phase("dispatch"):
                    t0 = time.perf_counter()
                    args = (
                        _pad_axis0(sbins[lo:hi], size, fill=bin_fill),
                        _pad_axis0(smm[lo:hi], size, fill=m),
                    )
                    if self.medoid_device_select:
                        # finalize inputs ride the same H2D put: tiny
                        # (B, M) metadata vs the (B, M, M) counts they
                        # replace on the D2H side.  Phantom rows carry
                        # all-False masks -> argmin 0, sliced away below.
                        args = args + (
                            _pad_axis0(batch.n_peaks[lo:hi], size),
                            _pad_axis0(batch.member_mask[lo:hi], size),
                            _pad_axis0(batch.n_members[lo:hi], size, fill=1),
                        )
                    args = (
                        self._ship(*args)
                        if self.mesh is not None
                        else self._put_batch(list(args))
                    )
                    if self.medoid_device_select:
                        res = self._kfn(
                            sim.medoid_select_packed,
                            sim.medoid_select_packed_donated,
                        )(*args, m=m, lcap=lcap)
                    else:
                        res = self._kfn(
                            sim.shared_bins_packed,
                            sim.shared_bins_packed_donated,
                        )(*args, m=m, lcap=lcap)
                    # slice on device first: D2H carries only real rows
                    res = res[: hi - lo]
                    dt = time.perf_counter() - t0  # see bin_mean: span nesting
                self._note_dispatch(
                    "medoid_select_packed" if self.medoid_device_select
                    else "shared_bins_packed",
                    (size, k, m, lcap, *tokens),
                    rows=hi - lo, padded_rows=size,
                    real_elems=lambda lo=lo, hi=hi: (smm[lo:hi] != m).sum(),
                    padded_elems=size * k,
                    seconds=dt, t_start=t0,
                )
                pending.append((batch, lo, hi, res))

        fetched = self._collect([p[-1] for p in pending])
        with st.phase("finalize"):
            for (batch, lo, hi, _), res in zip(pending, fetched):
                if self.medoid_device_select:
                    # res IS the winning index per cluster row
                    for ci in range(hi - lo):
                        out[batch.source_indices[lo + ci]] = int(res[ci])
                    continue
                # widen uint16 counts for the f64 finalize
                idx = medoid_finalize(
                    res.astype(np.int64),
                    batch.n_peaks[lo:hi],
                    batch.member_mask[lo:hi],
                    batch.n_members[lo:hi],
                )
                for ci in range(hi - lo):
                    out[batch.source_indices[lo + ci]] = int(idx[ci])
        return out

    def _medoid_indices_native(
        self, clusters: list[Cluster], config: MedoidConfig
    ) -> list[int]:
        """Host-native medoid counts (``native/medoid.cpp``): exact integer
        pairwise shared-bin counts by sorted merge in cache, threaded over
        clusters — mesh-less the link transfer dwarfs the gram matmul's
        FLOPs (round-4 bench: the device path spent more time in dispatch
        round trips than compute).  The float64 finalize is the SAME
        ``medoid_finalize`` the device path uses (grouped by member count
        in ``ops.medoid_native.finalize_indices``), so both paths share
        one fp semantics; the bucketized MXU path still carries mesh
        runs.  Split prepare/finish for the pipelined executor."""
        prepared = self._prepare_medoid(clusters, config, self.stats)
        if prepared is None:  # native lib raced away; callers checked
            raise RuntimeError("native medoid not built (make -C native)")
        return self._finish_medoid_indices(prepared)

    def _prepare_medoid(
        self, clusters: list[Cluster], config: MedoidConfig, st: RunStats
    ) -> PreparedChunk | None:
        """Pack stage of the native medoid path: the columnar table and
        its cluster-ordered peak views.  Returns None when the C++
        counter is unavailable — the bucketized device path packs per
        bucket and stays one-shot."""
        from specpride_tpu.data.packed import _as_table
        from specpride_tpu.ops import medoid_native

        if not medoid_native.available():
            return None
        _check_no_empty(clusters)
        with st.phase("pack"):
            table = _as_table(clusters)
            idx = table.cluster_order()
            mz_c, _, cnt = self._cluster_ordered_peaks(table, idx)
            spec_offsets = np.zeros(idx.order.size + 1, dtype=np.int64)
            np.cumsum(cnt, out=spec_offsets[1:])
            cso = np.zeros(table.n_clusters + 1, dtype=np.int64)
            np.cumsum(idx.n_members, out=cso[1:])
        return PreparedChunk(
            "medoid", "medoid_native", clusters, config, None,
            dict(mz_c=mz_c, cnt=cnt, spec_offsets=spec_offsets, cso=cso),
        )

    def _finish_medoid_indices(self, prepared: PreparedChunk) -> list[int]:
        from specpride_tpu.ops import medoid_native

        d = prepared.data
        st = self.stats
        with st.phase("compute"):
            shared_flat, out_offsets = medoid_native.shared_bin_counts(
                d["mz_c"], d["spec_offsets"], d["cso"],
                prepared.config.bin_size,
            )
        with st.phase("finalize"):
            indices = medoid_native.finalize_indices(
                shared_flat, out_offsets, d["cnt"], d["cso"]
            )
        st.count("clusters", len(prepared.clusters))
        return [int(i) for i in indices]

    @tracing.traced("method:medoid", backend="tpu")
    def run_medoid(
        self, clusters: list[Cluster], config: MedoidConfig = MedoidConfig()
    ) -> list[Spectrum]:
        faults.check("dispatch")
        indices = self.medoid_indices(clusters, config)
        return [c.members[i] for c, i in zip(clusters, indices)]

    # -- cross-job shared dispatch (serve.batcher) -----------------------

    def run_shared(
        self, method: str, parts, config, cos_config=None
    ) -> list:
        """Run one consensus/select method over clusters from SEVERAL
        sources as ONE batch-scoped prepare + dispatch group — the
        device half of the serving daemon's cross-job micro-batching
        (``serve.batcher``).  ``parts`` is a list of cluster lists (one
        per tenant job); they are merged into a single pack input, so
        the bucket planner fills buckets across jobs and the fixed
        dispatch overhead is paid once instead of per job.

        Per-cluster independence (the same property that makes output
        chunk-invariant) guarantees each cluster's representative — and
        its QC cosine, computed when ``cos_config`` is given — is
        bit-identical to a solo run over that source alone; provenance
        spans from ``merge_cluster_sources`` scatter results back.

        Returns one ``(representatives, cosines-or-None)`` pair per
        source, aligned with that source's cluster order."""
        from specpride_tpu.data.packed import merge_cluster_sources

        merged, spans = merge_cluster_sources(parts)
        cosines = None
        if method == "bin-mean":
            if cos_config is not None:
                reps, cosines = self.run_bin_mean_with_cosines(
                    merged, config, cos_config
                )
            else:
                reps = self.run_bin_mean(merged, config)
        elif method == "gap-average":
            reps = self.run_gap_average(merged, config)
        elif method == "medoid":
            reps = self.run_medoid(merged, config)
        else:
            raise ValueError(f"method {method!r} is not batchable")
        if len(reps) != len(merged):
            # a method dropped clusters (should not happen for the
            # batchable methods, which are total): the span scatter
            # would misalign — refuse rather than mis-scatter
            raise RuntimeError(
                f"shared {method} dispatch returned {len(reps)} "
                f"representatives for {len(merged)} clusters"
            )
        if cos_config is not None and cosines is None:
            cosines = self.average_cosines(reps, merged, cos_config)
        out = []
        for start, stop in spans:
            out.append((
                reps[start:stop],
                None if cosines is None else cosines[start:stop],
            ))
        return out

    # -- best-spectrum representative (host-only; ref src/best_spectrum.py) --

    def run_best_spectrum(
        self,
        clusters: list[Cluster],
        scores: dict[str, float],
        config: BestSpectrumConfig = BestSpectrumConfig(),
    ) -> list[Spectrum]:
        """Pure join/argmax — negligible compute, host-side by design
        (survey §3.4)."""
        return numpy_backend.run_best_spectrum(clusters, scores, config)

    # -- quality metrics (K2 cosine) ------------------------------------

    @tracing.traced("method:cosine", backend="tpu")
    def average_cosines(
        self,
        representatives: list[Spectrum],
        clusters: list[Cluster],
        config: CosineConfig = CosineConfig(),
    ) -> np.ndarray:
        """Mean binned cosine of each representative to its cluster's members
        (ref src/benchmark.py:31-38) on the packed layout: device receives
        packed peaks + f64-quantized grid bins, returns only the per-member
        cosines (``ops.similarity.cosine_packed``)."""
        from specpride_tpu.data.packed import pack_bucketize
        from specpride_tpu.ops import similarity as sim

        cosine_packed = self._kfn(
            sim.cosine_packed, sim.cosine_packed_donated
        )
        if len(representatives) != len(clusters):
            raise ValueError("representatives and clusters must align")
        _check_no_empty(clusters)
        if self.mesh is None and self.layout == "auto":
            from specpride_tpu.ops import cosine_native

            if cosine_native.available():
                return self._average_cosines_native(
                    representatives, clusters, config
                )
        if self.mesh is None and self.layout != "bucketized":
            return self._average_cosines_flat(representatives, clusters, config)
        space = config.mz_space
        out = np.zeros((len(clusters),), dtype=np.float64)
        pending = []
        st = self.stats
        for batch in self._timed_batches(
            pack_bucketize(clusters, self.batch_config)
        ):
            idxs = batch.source_indices
            b, k = batch.mz.shape
            m = batch.m
            with st.phase("pack"):
                pr_raw = max(
                    max((representatives[i].n_peaks for i in idxs), default=1),
                    1,
                )
                # shape-stable (one compile per value)
                pr = _pow2(pr_raw, floor=256)
                rep_mz = np.zeros((b, pr), np.float64)
                rep_int = np.zeros((b, pr), np.float32)
                rep_valid = np.zeros((b, pr), bool)
                mem_edges = np.zeros((b, m), np.int32)
                for ci, gi in enumerate(idxs):
                    r = representatives[gi]
                    rep_mz[ci, : r.n_peaks] = r.mz
                    rep_int[ci, : r.n_peaks] = quantize.cosine_normalize(
                        r.intensity, config
                    )
                    rep_valid[ci, : r.n_peaks] = True
                    for mi, mem in enumerate(clusters[gi].members):
                        if mem.n_peaks:
                            # per-member edge count off the LAST peak
                            # (ref src/benchmark.py:20, assumes sorted)
                            mem_edges[ci, mi] = quantize.cosine_edge_count(
                                mem.mz[-1], space
                            )
                rep_bins, rep_edges = quantize.cosine_bins(
                    rep_mz, rep_valid, config
                )
                mem_bins, _ = quantize.cosine_bins(
                    batch.mz64, batch.member_id >= 0, config
                )

                # host pre-sort (device sorts were the dominant kernel cost):
                # rep rows by bin; member rows by (member, bin) with padding
                # mapped to m so it sorts last.  Sentinels (2**30) stay well
                # below the composite-key bounds.
                r_order = np.argsort(rep_bins, axis=1, kind="stable")
                rep_bins = np.take_along_axis(rep_bins, r_order, axis=1)
                rep_int = np.take_along_axis(rep_int, r_order, axis=1)
                mm = np.where(batch.member_id >= 0, batch.member_id, m).astype(
                    np.int64
                )
                key = mm * (1 << 31) + mem_bins
                m_order = np.argsort(key, axis=1, kind="stable")
                mem_bins = np.take_along_axis(mem_bins, m_order, axis=1)
                mem_int = np.take_along_axis(
                    quantize.cosine_normalize(batch.intensity, config)
                    .astype(np.float32),
                    m_order, axis=1,
                )
                mem_mm = np.take_along_axis(
                    mm.astype(np.int32), m_order, axis=1
                )

            chunk = max(1, self.max_grid_elements // max((k + pr) * 6, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                with st.phase("dispatch"):
                    t0 = time.perf_counter()
                    mean, _ = cosine_packed(
                        *self._ship(
                            _pad_axis0(rep_bins[lo:hi], size, fill=2**30),
                            _pad_axis0(rep_int[lo:hi], size),
                            _pad_axis0(rep_edges[lo:hi], size),
                            _pad_axis0(mem_bins[lo:hi], size, fill=2**30),
                            _pad_axis0(mem_int[lo:hi], size),
                            _pad_axis0(mem_mm[lo:hi], size, fill=m),
                            _pad_axis0(mem_edges[lo:hi], size),
                            _pad_axis0(batch.member_mask[lo:hi], size),
                            _pad_axis0(batch.n_members[lo:hi], size),
                        ),
                        m=m,
                    )
                    dt = time.perf_counter() - t0  # see bin_mean: span nesting
                self._note_dispatch(
                    "cosine_packed", (size, k, pr, m),
                    rows=hi - lo, padded_rows=size,
                    real_elems=lambda lo=lo, hi=hi: (mem_mm[lo:hi] != m).sum(),
                    padded_elems=size * k,
                    seconds=dt, t_start=t0,
                )
                pending.append((idxs, lo, hi, mean))

        means = self._collect([p[-1] for p in pending])
        with st.phase("finalize"):
            for (idxs, lo, hi, _), mean in zip(pending, means):
                for ci in range(hi - lo):
                    out[idxs[lo + ci]] = float(mean[ci])
        return out

    @tracing.traced("method:bin_mean_with_cosines", backend="tpu")
    def run_bin_mean_with_cosines(
        self,
        clusters: list[Cluster],
        bin_config: BinMeanConfig = BinMeanConfig(),
        cos_config: CosineConfig = CosineConfig(),
    ) -> tuple[list[Spectrum], np.ndarray]:
        """Consensus + QC in one pass (the CLI evaluate flow and the
        headline pipeline): bin-mean representatives AND their mean member
        cosines.

        Beyond composing ``run_bin_mean`` + ``average_cosines``, the
        mesh-less path OVERLAPS the representative-independent half of the
        cosine prep (the expensive member gathers/sorts) with the bin-mean
        kernel and its D2H stream — on tunneled hosts the device->host
        link runs at ~25 MB/s, so the consensus transfer is the pipeline's
        critical path and the host would otherwise sit idle under it."""
        faults.check("dispatch")
        if self.mesh is not None or self.layout == "bucketized":
            reps = self.run_bin_mean(clusters, bin_config)
            return reps, self.average_cosines(reps, clusters, cos_config)

        # host ("auto") and flat layouts: one shared pack stage, then the
        # kind-matched compute stage (host run reductions + interleaved
        # native C++ cosine, or flat device dispatch + async D2H).
        # member_prep=False: serially, the flat member-cosine prep belongs
        # AFTER dispatch, hidden under the consensus D2H stream.
        prepared = self._prepare_bin_mean(
            clusters, bin_config, cos_config, self.stats, member_prep=False
        )
        reps, cosines = self._finish_bin_mean(prepared)
        return reps, cosines

    def _emit_bin_mean_rows(self, batch, fused, aux, clusters, out) -> None:
        """Assemble one flat chunk's Spectrum slots from the HOST m/z means
        (``aux["kept_mz"]``) and the device's compacted intensity means
        (shared by the serial flat finish and the pipelined native path)."""
        flat_int = np.asarray(fused)
        off = aux["row_out_offsets"]
        if getattr(batch, "scale", None) is not None:
            # int8 packed path: the device averaged 7-bit CODES; means
            # are linear, so the per-cluster scale applies here instead
            # of ever crossing the link
            n_tot = int(off[-1])
            flat_int = flat_int.astype(np.float64, copy=True)
            flat_int[:n_tot] *= np.repeat(batch.scale, np.diff(off))
        kept_mz = aux["kept_mz"]
        for ci in range(aux["rows"]):
            o0, o1 = int(off[ci]), int(off[ci + 1])
            gi = batch.source_indices[ci]
            members = clusters[gi].members
            out[gi] = Spectrum(
                # copies: slices would pin the chunk-wide buffers alive
                mz=kept_mz[o0:o1].copy(),
                intensity=flat_int[o0:o1].astype(np.float64),
                # exact f64 mean, as the oracle (ref src/binning.py:224)
                precursor_mz=float(
                    np.mean([s.precursor_mz for s in members])
                ),
                precursor_charge=members[0].precursor_charge,
                title=batch.cluster_ids[ci],
            )

    @staticmethod
    def _cluster_ordered_peaks(table, idx):
        """``(mz, intensity, cnt)`` with spectra grouped by cluster in
        code order — ZERO-COPY views when the table is already
        cluster-contiguous (the common CLI case: the parser emits spectra
        in file order and clusters are file-grouped), one gather
        otherwise."""
        from specpride_tpu.data.packed import _grouped_arange

        cnt = table.peak_counts[idx.order]
        if np.array_equal(idx.order, np.arange(idx.order.size)):
            return table.mz, table.intensity, cnt
        src = np.repeat(
            table.peak_offsets[idx.order], cnt
        ) + _grouped_arange(cnt)
        return table.mz[src], table.intensity[src], cnt

    def _prep_cosine_native(self, clusters, config: CosineConfig):
        """Representative-independent half of the NATIVE cosine path: the
        flat member layout (at most one gather off the columnar table — no
        quantization, no sort: the C++ kernel bins on the fly in cache).
        Split out so the fused pipeline can run it while the consensus
        kernel and its D2H stream are in flight."""
        from specpride_tpu.data.packed import _as_table

        table = _as_table(clusters)
        idx = table.cluster_order()
        mem_mz, mem_int, cnt = self._cluster_ordered_peaks(table, idx)
        spec_offsets = np.zeros(idx.order.size + 1, dtype=np.int64)
        np.cumsum(cnt, out=spec_offsets[1:])
        cso = np.zeros(table.n_clusters + 1, dtype=np.int64)
        np.cumsum(idx.n_members, out=cso[1:])
        return dict(
            mem_mz=mem_mz,
            mem_int=quantize.cosine_normalize(mem_int, config),
            spec_offsets=spec_offsets,
            cluster_spec_offsets=cso,
            n_members=idx.n_members,
        )

    def _cosine_native_rows(
        self, representatives, mprep, config, lo: int, hi: int
    ) -> np.ndarray:
        """Mean member cosine for cluster rows [lo, hi) via the native
        kernel (``native/cosine.cpp``); ``representatives`` is the
        (hi - lo)-length slice for exactly those rows; ``mprep`` from
        ``_prep_cosine_native``."""
        from specpride_tpu.ops import cosine_native

        reps = representatives
        if len(reps) != hi - lo:
            raise ValueError("representatives slice must match [lo, hi)")
        rep_offsets = np.zeros(hi - lo + 1, dtype=np.int64)
        np.cumsum([r.n_peaks for r in reps], out=rep_offsets[1:])
        rep_mz = (
            np.concatenate([np.asarray(r.mz, np.float64) for r in reps])
            if rep_offsets[-1]
            else np.zeros(0, np.float64)
        )
        rep_int = (
            np.concatenate([np.asarray(r.intensity, np.float64) for r in reps])
            if rep_offsets[-1]
            else np.zeros(0, np.float64)
        )
        rep_int = quantize.cosine_normalize(rep_int, config)
        cso = mprep["cluster_spec_offsets"]
        s0, s1 = int(cso[lo]), int(cso[hi])
        p0 = int(mprep["spec_offsets"][s0])
        p1 = int(mprep["spec_offsets"][s1])
        cos = cosine_native.pair_cosines(
            rep_mz,
            rep_int,
            rep_offsets,
            mprep["mem_mz"][p0:p1],
            mprep["mem_int"][p0:p1],
            mprep["spec_offsets"][s0 : s1 + 1] - p0,
            cso[lo : hi + 1] - s0,
            config.mz_space,
        )
        # mean over members; summation-order difference vs the oracle's
        # np.mean (pairwise) is ~1e-16 relative
        nm = mprep["n_members"][lo:hi].astype(np.float64)
        sums = np.add.reduceat(
            np.concatenate([cos, [0.0]]), cso[lo:hi] - s0
        )[: hi - lo]
        return sums / np.maximum(nm, 1.0)

    def _average_cosines_native(
        self,
        representatives: list[Spectrum],
        clusters: list[Cluster],
        config: CosineConfig,
    ) -> np.ndarray:
        """Host-native K2b path (``native/cosine.cpp``): exact-f64 oracle
        semantics, threaded over clusters, no packing/padding and no device
        round trip — the measured winner mesh-less (see the kernel header
        for the link economics; the flat/bucketized device paths remain for
        mesh runs)."""
        st = self.stats
        with st.phase("pack"):
            mprep = self._prep_cosine_native(clusters, config)
        with st.phase("compute"):
            out = self._cosine_native_rows(
                representatives, mprep, config, 0, len(clusters)
            )
        st.count("clusters", len(clusters))
        return out

    def _average_cosines_flat(
        self,
        representatives: list[Spectrum],
        clusters: list[Cluster],
        config: CosineConfig,
    ) -> np.ndarray:
        """Flat zero-padding K2b path (``ops.similarity.cosine_flat``):
        member peaks and rep peaks each travel as ONE flat sorted axis with
        int32 (row, bin) composite keys — no bucket padding, no per-cluster
        Python fill loop, one dispatch per ~max_grid_elements peaks."""
        st = self.stats
        with st.phase("pack"):
            prep = self._prep_cosine_flat(representatives, clusters, config)
        return self._dispatch_cosine_flat(prep)

    def _prep_cosine_flat(self, representatives, clusters, config):
        mprep = self._prep_cosine_members(clusters, config)
        return self._prep_cosine_reps(representatives, mprep, config)

    def _prep_cosine_members(self, clusters, config):
        """Representative-INDEPENDENT half of the cosine prep (the flat
        member layout: gathers, f64 quantization, segmented bin sort).
        Split out so the fused consensus+QC pipeline can run it while the
        bin-mean kernel and its D2H stream are still in flight."""
        from specpride_tpu.data.packed import _as_table, _grouped_arange

        table = _as_table(clusters)
        idx = table.cluster_order()
        c = table.n_clusters
        space = config.mz_space

        # --- member flat arrays, sorted by (row, member, bin)
        order = idx.order  # spectrum ids grouped by cluster code
        sorted_code = table.cluster_code[order]
        cnt = table.peak_counts[order]
        row_pk = np.repeat(sorted_code, cnt)
        src = np.repeat(table.peak_offsets[order], cnt) + _grouped_arange(cnt)
        mz64 = table.mz[src]
        inten = quantize.cosine_normalize(
            table.intensity[src], config
        ).astype(np.float32)
        cbin = np.maximum(
            np.floor((mz64 + space / 2.0) / space).astype(np.int64), 0
        )
        # per-spectrum edge count off the LAST peak in ORIGINAL order
        # (ref src/benchmark.py:20 assumes sorted spectra; parity demands
        # the last element, not the max)
        has = cnt > 0
        last_pos = table.peak_offsets[order] + np.maximum(cnt - 1, 0)
        last_mz = np.where(has, table.mz[np.minimum(last_pos,
                                                    table.mz.size - 1)],
                           -np.inf)
        spec_edges = quantize.cosine_edge_count(last_mz, space)

        # spectra are already (row, member)-grouped, so the lexsort reduces
        # to sorting each spectrum's peaks by bin — segmented, threaded.
        # The same cumsum doubles as the per-spectrum extent table the
        # kernel receives (each spectrum's peaks stay contiguous).
        from specpride_tpu.ops.segsort import seg_argsort

        spec_start = np.zeros(order.size + 1, dtype=np.int64)
        np.cumsum(cnt, out=spec_start[1:])
        perm = seg_argsort(cbin, spec_start)
        cbin = cbin[perm]
        inten = inten[perm]

        # scan-window caps for the kernel's segmented scans (ops.segments):
        # the longest same-(spectrum, bin) duplicate run and the largest
        # spectrum, computed from the sorted pass (floors bound the number
        # of distinct compile classes)
        spec_of_peak_sorted = np.repeat(
            np.arange(order.size, dtype=np.int64), cnt
        )
        l_mem = _pow2(
            int(_max_run_len(spec_of_peak_sorted * (1 << 31) + cbin)), floor=4
        )
        l_spec = _pow2(int(cnt.max(initial=1)), floor=256)

        return dict(
            table=table, idx=idx, c=c, sorted_code=sorted_code, cnt=cnt,
            cbin=cbin, inten=inten, spec_start=spec_start,
            spec_edges=spec_edges, row_pk=row_pk,
            spec_of_peak_sorted=spec_of_peak_sorted,
            l_mem=l_mem, l_spec=l_spec,
        )

    def _prep_cosine_reps(self, representatives, mprep, config):
        """Representative-DEPENDENT half of the cosine prep (rep layout,
        edge gating, composite-key budget)."""
        idx = mprep["idx"]
        c = mprep["c"]
        sorted_code = mprep["sorted_code"]
        cbin = mprep["cbin"]
        inten = mprep["inten"]
        spec_start = mprep["spec_start"]
        spec_edges = mprep["spec_edges"]
        row_pk = mprep["row_pk"]
        spec_of_peak_sorted = mprep["spec_of_peak_sorted"]
        l_mem = mprep["l_mem"]
        l_spec = mprep["l_spec"]
        space = config.mz_space

        # --- rep flat arrays, sorted by (row, bin)
        rep_counts = np.array(
            [representatives[i].n_peaks for i in range(c)], dtype=np.int64
        )
        rep_mz = (
            np.concatenate([np.asarray(representatives[i].mz, np.float64)
                            for i in range(c)])
            if rep_counts.sum()
            else np.zeros(0, np.float64)
        )
        rep_in = (
            quantize.cosine_normalize(
                np.concatenate([
                    np.asarray(representatives[i].intensity, np.float64)
                    for i in range(c)
                ]),
                config,
            ).astype(np.float32)
            if rep_counts.sum()
            else np.zeros(0, np.float32)
        )
        rep_row = np.repeat(np.arange(c, dtype=np.int64), rep_counts)
        rbin = np.maximum(
            np.floor((rep_mz + space / 2.0) / space).astype(np.int64), 0
        )
        rep_last = np.array(
            [
                representatives[i].mz[-1] if representatives[i].n_peaks else
                -np.inf
                for i in range(c)
            ],
            dtype=np.float64,
        )
        rep_edges_all = quantize.cosine_edge_count(rep_last, space)
        rperm = np.lexsort((rbin, rep_row))
        rep_row = rep_row[rperm]
        rbin = rbin[rperm]
        rep_in = rep_in[rperm]
        rep_offsets_all = np.zeros(c + 1, dtype=np.int64)
        np.cumsum(rep_counts, out=rep_offsets_all[1:])
        row_peak_offsets = np.zeros(c + 1, dtype=np.int64)
        np.cumsum(idx.total_peaks, out=row_peak_offsets[1:])

        max_bin = int(
            max(
                cbin.max(initial=0),
                rbin.max(initial=0),
                int(np.max(spec_edges, initial=0)),
                int(np.max(rep_edges_all, initial=0)),
            )
        )
        # shift floor kept low: every doubling of shift halves the rows one
        # dispatch can carry, and each dispatch pays ~0.1 s of tunnel
        # round-trip on remote-device hosts
        shift = _pow2(max_bin + 2, floor=1 << 16)
        max_rows_cap = max((2**31 - 2) // shift, 1)
        # rows_cap (pow2) must stay under the composite budget
        max_rows = max(1 << (max_rows_cap.bit_length() - 1), 1)

        # scan caps for the rep side: duplicate-bin runs within one rep and
        # the largest rep (rows are contiguous in (row, bin) order)
        l_rep = _pow2(
            int(_max_run_len(rep_row * np.int64(1 << 31) + rbin)), floor=4
        )
        l_row = _pow2(int(rep_counts.max(initial=1)), floor=256)

        # host-side edge gating: the pair cutoff (max of rep/member edge
        # counts - 2, ref src/benchmark.py:20-22) zeroes failing peaks IN
        # the shipped intensity, so the kernel needs no per-element gather
        # from per-spectrum tables (XLA lowers those to one-hot matmuls —
        # a measured 84 GB of HBM traffic per chunk)
        cut_spec_all = (
            np.maximum(rep_edges_all[sorted_code], spec_edges) - 2
        )  # (S,)
        cut_at = (
            cut_spec_all[spec_of_peak_sorted]
            if spec_of_peak_sorted.size
            else np.zeros(0, np.int64)
        )
        inten_gated = np.where(cbin <= cut_at, inten, 0.0).astype(np.float32)

        return dict(
            c=c, sorted_code=sorted_code, spec_start=spec_start, cbin=cbin,
            inten_gated=inten_gated, idx=idx, rep_row=rep_row,
            rbin=rbin, rep_in=rep_in, rep_offsets_all=rep_offsets_all,
            row_peak_offsets=row_peak_offsets,
            # row/spectrum of each peak in the permuted flat order (the
            # lexsort is stable within already-(row, member)-grouped
            # arrays, so the pre-perm grouping survives)
            row_elem=row_pk, spec_elem=spec_of_peak_sorted,
            cut_spec_all=cut_spec_all,
            shift=shift, max_rows=max_rows,
            l_rep=l_rep, l_row=l_row, l_spec=l_spec, l_mem=l_mem,
            l_members=_pow2(int(idx.max_members), floor=32),
        )

    def _dispatch_cosine_flat(self, prep: dict) -> np.ndarray:
        from specpride_tpu.ops import similarity as sim

        cosine_flat = self._kfn(sim.cosine_flat, sim.cosine_flat_donated)
        st = self.stats
        c = prep["c"]
        sorted_code = prep["sorted_code"]
        spec_start = prep["spec_start"]
        cbin = prep["cbin"]
        inten_gated = prep["inten_gated"]
        idx = prep["idx"]
        rep_row = prep["rep_row"]
        rbin = prep["rbin"]
        rep_in = prep["rep_in"]
        rep_offsets_all = prep["rep_offsets_all"]
        row_peak_offsets = prep["row_peak_offsets"]
        row_elem = prep["row_elem"]
        spec_elem_all = prep["spec_elem"]
        cut_spec_all = prep["cut_spec_all"]
        shift = prep["shift"]
        max_rows = prep["max_rows"]

        sent = np.int32(2**31 - 1)
        out = np.zeros((c,), dtype=np.float64)
        pending = []
        lo = 0
        budget = self.max_grid_elements // 4
        while lo < c:
            hi = min(lo + max_rows, c)
            while (
                hi > lo + 1
                and row_peak_offsets[hi] - row_peak_offsets[lo] > budget
            ):
                hi = lo + max(
                    int(
                        np.searchsorted(
                            row_peak_offsets[lo + 1 : hi + 1],
                            row_peak_offsets[lo] + budget,
                            side="right",
                        )
                    ),
                    1,
                )
            rows = hi - lo
            with st.phase("pack"):
                rows_cap = _pow2(rows, floor=min(64, max_rows))
                p0, p1 = int(row_peak_offsets[lo]), int(row_peak_offsets[hi])
                n = p1 - p0
                n_pad = _pow2(n, floor=1024)
                # spectra of this chunk (sorted_code is non-decreasing over
                # `order`: a searchsorted window covers exactly rows [lo, hi))
                s0 = int(np.searchsorted(sorted_code, lo, side="left"))
                s1 = int(np.searchsorted(sorted_code, hi, side="left"))
                s_real = s1 - s0
                # pow2-padded like every other kernel input (shapes key the
                # jit cache); the +1 guarantees at least one fill slot, which
                # absorbs the padded peak tail as a zero-contribution
                # spectrum mapped to the last row
                s_pad = _pow2(s_real + 1, floor=64)
                spec_offsets = np.full(s_pad + 1, n_pad, dtype=np.int32)
                spec_offsets[: s_real + 1] = spec_start[s0 : s1 + 1] - p0
                spec_row = np.full(s_pad, rows_cap - 1, dtype=np.int32)
                spec_row[:s_real] = (sorted_code[s0:s1] - lo).astype(np.int32)
                # spectrum extents per row (rows are contiguous in the
                # spectrum axis); fill rows own empty extents
                row_spec_offsets = np.full(rows_cap + 1, s_real,
                                           dtype=np.int32)
                row_spec_offsets[: rows + 1] = (
                    np.searchsorted(sorted_code, np.arange(lo, hi + 1)) - s0
                ).astype(np.int32)
                r0 = int(rep_offsets_all[lo])
                r1 = int(rep_offsets_all[hi])
                nr = r1 - r0
                nr_pad = _pow2(nr, floor=256)
                rkey = np.full(nr_pad, sent, dtype=np.int32)
                rkey[:nr] = (
                    (rep_row[r0:r1] - lo) * np.int64(shift) + rbin[r0:r1]
                ).astype(np.int32)
                rep_offsets = np.zeros(rows_cap + 1, dtype=np.int32)
                rep_offsets[: rows + 1] = (
                    rep_offsets_all[lo : hi + 1] - r0
                ).astype(np.int32)
                rep_offsets[rows + 1 :] = rep_offsets[rows]
                nm = np.zeros(rows_cap, dtype=np.int32)
                nm[:rows] = idx.n_members[lo:hi]
                # per-peak channels, host-gated and host-composited
                mkey = np.full(n_pad, sent, dtype=np.int32)
                mkey[:n] = (
                    (row_elem[p0:p1] - lo) * np.int64(shift) + cbin[p0:p1]
                ).astype(np.int32)
                mint = np.zeros(n_pad, dtype=np.float32)
                mint[:n] = inten_gated[p0:p1]
                spec_elem = np.full(n_pad, s_real, dtype=np.int32)
                spec_elem[:n] = (spec_elem_all[p0:p1] - s0).astype(np.int32)
                # rep lookup: last element of the matching rep run
                # (threaded native searchsorted — ~3M queries per batch)
                from specpride_tpu.ops.segsort import searchsorted_right_i32

                pos = (searchsorted_right_i32(rkey, mkey) - 1).astype(
                    np.int32
                )
                # rep-norm cutoff position per spectrum
                npos = np.zeros(s_pad, dtype=np.int32)
                npos[:s_real] = np.searchsorted(
                    rkey,
                    (sorted_code[s0:s1] - lo) * np.int64(shift)
                    + cut_spec_all[s0:s1] + 1,
                ).astype(np.int32)

            with st.phase("dispatch"):
                t0 = time.perf_counter()
                mean = cosine_flat(
                    *self._put_batch([
                        rkey,
                        np.pad(rep_in[r0:r1], (0, nr_pad - nr)),
                        mkey,
                        mint,
                        spec_elem,
                        pos,
                        spec_offsets,
                        spec_row,
                        npos,
                        rep_offsets,
                        row_spec_offsets,
                        nm,
                    ]),
                    shift=shift,
                    l_rep=prep["l_rep"],
                    l_row=prep["l_row"],
                    l_spec=prep["l_spec"],
                    l_mem=prep["l_mem"],
                    l_members=prep["l_members"],
                )
                dt = time.perf_counter() - t0  # see bin_mean: span nesting
            self._note_dispatch(
                # shape class keyed by EVERY static jit arg (the scan
                # windows and key shift define distinct compiles too), so
                # the shape manifest can rebuild the exact compilation
                "cosine_flat",
                (
                    n_pad, nr_pad, rows_cap, s_pad, shift,
                    prep["l_rep"], prep["l_row"], prep["l_spec"],
                    prep["l_mem"], prep["l_members"],
                ),
                rows=rows, padded_rows=rows_cap,
                real_elems=n, padded_elems=n_pad,
                seconds=dt, t_start=t0,
            )
            pending.append((lo, rows, mean))
            lo = hi

        means = self._collect([p[-1] for p in pending])
        with st.phase("finalize"):
            for (lo, rows, _), mean in zip(pending, means):
                out[lo : lo + rows] = mean[:rows]
        return out
