"""NumPy oracle implementations of every merge strategy and metric.

Each function is a from-scratch behavioural reimplementation of a reference
algorithm, cited per function.  Divergences from the reference are limited to
(a) crash bugs we refuse to reproduce and (b) explicitly flagged config
switches; each is called out in the docstring of the function concerned.
"""

from __future__ import annotations

import numpy as np

from specpride_tpu.config import (
    BestSpectrumConfig,
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.ops import quantize
from specpride_tpu.ops.fragments import PROTON_MASS
# fault-injection site (no-op unless a FaultPlan is armed): the oracle
# shares the tpu backend's "dispatch" site so a chaos run exercises the
# same recovery paths whichever --backend is selected
from specpride_tpu.robustness import faults


def check_uniform_charge(members: list[Spectrum]) -> None:
    """All precursor charges in a cluster must be equal (ref
    src/binning.py:206 assert → ValueError here).  Shared by the numpy and
    TPU bin-mean drivers so the rule lives in exactly one place."""
    charges = [s.precursor_charge for s in members]
    if any(z != charges[0] for z in charges):
        raise ValueError("Not all precursor charges in cluster are equal")


# ---------------------------------------------------------------------------
# C1: binned-mean consensus (ref src/binning.py:170-231 combine_bin_mean)
# ---------------------------------------------------------------------------

def bin_mean_consensus(
    members: list[Spectrum],
    config: BinMeanConfig = BinMeanConfig(),
    cluster_id: str = "",
) -> Spectrum:
    """Grid-bin all member peaks and take per-bin means.

    Semantics reproduced from ref src/binning.py:170-231:

    * bin index ``int((mz - min) / binsize)`` over [min_mz, max_mz)
    * quorum ``int(n_members * 0.25) + 1`` — a bin kept only if at least that
      many members contributed a peak
    * numpy fancy-index ``+=`` buffering: when one member has several peaks
      in the same bin, only the LAST such peak contributes (and the member is
      counted once) — ref src/binning.py:197-199.  Reproduced here by the
      same numpy construct.
    * per-bin mean m/z and mean intensity, means over contributing members
    * precursor m/z = mean over members; all charges must be equal
      (ref src/binning.py:206 assert → here a ValueError)
    """
    n_bins = config.n_bins
    counts = np.zeros(n_bins, dtype=np.int32)
    inten_sum = np.zeros(n_bins, dtype=np.float32)
    mz_sum = np.zeros(n_bins, dtype=np.float32)

    check_uniform_charge(members)
    charges = [s.precursor_charge for s in members]

    for s in members:
        # grid quantization shared with the device packers
        # (ops.quantize.bin_mean_bins): "da" fixed grid or "ppm"
        # mass-proportional bins
        bins64, keep = quantize.bin_mean_bins(s.mz, config)
        mz = s.mz[keep]
        inten = s.intensity[keep]
        bins = bins64[keep]
        # numpy buffered fancy-index += : duplicate bins within this member
        # collapse to the last occurrence (ref src/binning.py:197-199)
        counts[bins] += 1
        inten_sum[bins] += inten.astype(np.float32)
        mz_sum[bins] += mz.astype(np.float32)

    quorum = 1
    if config.apply_peak_quorum:
        quorum = int(len(members) * config.quorum_fraction) + 1

    with np.errstate(invalid="ignore", divide="ignore"):
        inten_mean = np.where(counts < quorum, np.nan, inten_sum)
        inten_mean = inten_mean / counts
        mz_mean = np.where(mz_sum == 0, np.nan, mz_sum) / counts

    keep_mask = ~np.isnan(inten_mean)
    return Spectrum(
        mz=mz_mean[keep_mask].astype(np.float64),
        intensity=inten_mean[keep_mask].astype(np.float64),
        precursor_mz=float(np.mean([s.precursor_mz for s in members])),
        precursor_charge=charges[0] if charges else 0,
        title=cluster_id,
    )


# ---------------------------------------------------------------------------
# C2: gap-clustered average consensus
# (ref src/average_spectrum_clustering.py:26-103 average_spectrum)
# ---------------------------------------------------------------------------

def gap_average_consensus(
    members: list[Spectrum],
    config: GapAverageConfig = GapAverageConfig(),
    cluster_id: str = "",
    precursor_mz: float = 0.0,
    precursor_charge: int = 0,
    rt: float = 0.0,
) -> Spectrum:
    """Sort-concatenated peaks, split at m/z gaps >= mz_accuracy, average
    each group, keep groups spanning >= min_fraction of members, then apply
    the dynamic-range floor (max / dyn_range).

    Group semantics reproduced from ref src/average_spectrum_clustering.py:
    group mean m/z = group_sum / group_size but group intensity =
    group_sum / n_members (ref :76-77,81-82,86-87).  ``config.tail_mode ==
    "reference"`` also reproduces the loop over ``ind_list[1:-1]`` (ref
    :79-87): with >= 2 gaps, the final gap is ignored and the last two groups
    merge.  Divergences (reference crashes we fix): zero gaps → one group;
    all groups failing quorum → empty output (ref would crash on
    ``.max()`` of an empty array at :95).
    """
    if not members:
        raise ValueError("cannot average an empty cluster")

    if len(members) == 1:
        new_mz = members[0].mz.copy()
        new_inten = members[0].intensity.copy()
    else:
        # grouping (sort + f64 gap detection + tail-mode) lives in ONE place,
        # shared with the device pack path — ops.quantize.gap_segments
        mz_all, inten_all, seg = quantize.gap_segments(members, config)
        n_groups = int(seg[-1]) + 1 if mz_all.size else 0
        sizes = np.bincount(seg, minlength=n_groups)
        group_mz = np.bincount(seg, weights=mz_all, minlength=n_groups) / sizes
        group_inten = np.bincount(
            seg, weights=inten_all, minlength=n_groups
        ) / len(members)

        min_l = config.min_fraction * len(members)
        quorum_ok = sizes >= min_l
        new_mz = group_mz[quorum_ok]
        new_inten = group_inten[quorum_ok]

    if new_inten.size:
        floor = new_inten.max() / config.dyn_range
        keep = new_inten >= floor
        new_mz, new_inten = new_mz[keep], new_inten[keep]

    return Spectrum(
        mz=new_mz,
        intensity=new_inten,
        precursor_mz=precursor_mz,
        precursor_charge=precursor_charge,
        rt=rt,
        title=cluster_id,
    )


# --- precursor-mass / RT estimators
# (ref src/average_spectrum_clustering.py:106-148) -------------------------

def _neutral_masses(members: list[Spectrum]) -> tuple[np.ndarray, np.ndarray]:
    """m*z - z*H per member (ref src/average_spectrum_clustering.py:134-138)."""
    mzs = np.array([s.precursor_mz for s in members])
    charges = np.array([s.precursor_charge for s in members])
    return mzs * charges - charges * PROTON_MASS, charges


def _lower_median_index(values: np.ndarray) -> int:
    """Index of the lower median: sorted rank (n-1)//2
    (ref src/average_spectrum_clustering.py:106-110)."""
    order = np.argsort(values)
    return int(order[(len(values) - 1) // 2])


def naive_average_mass_and_charge(members: list[Spectrum]) -> tuple[float, int]:
    """Mean precursor m/z; all charges must agree
    (ref src/average_spectrum_clustering.py:127-132)."""
    charges = {s.precursor_charge for s in members}
    if len(charges) > 1:
        raise ValueError(
            "There are different charge states in the cluster. "
            "Cannot average precursor m/z."
        )
    return float(np.mean([s.precursor_mz for s in members])), charges.pop()


def neutral_average_mass_and_charge(members: list[Spectrum]) -> tuple[float, int]:
    """Mean neutral mass re-charged at the rounded mean charge
    (ref src/average_spectrum_clustering.py:140-144)."""
    masses, charges = _neutral_masses(members)
    z = int(round(float(np.mean(charges))))
    return (float(np.mean(masses)) + z * PROTON_MASS) / z, z


def lower_median_mass_and_charge(members: list[Spectrum]) -> tuple[float, int]:
    """Lower-median neutral mass, converted back at that member's charge
    (ref src/average_spectrum_clustering.py:112-116)."""
    masses, charges = _neutral_masses(members)
    i = _lower_median_index(masses)
    z = int(charges[i])
    return (float(masses[i]) + z * PROTON_MASS) / z, z


def median_rt(members: list[Spectrum]) -> float:
    """(ref src/average_spectrum_clustering.py:146-148)"""
    return float(np.median([s.rt for s in members]))


def lower_median_mass_rt(members: list[Spectrum]) -> float:
    """RT of the lower-median-mass member
    (ref src/average_spectrum_clustering.py:118-122)."""
    masses, _ = _neutral_masses(members)
    return float(members[_lower_median_index(masses)].rt)


PEPMASS_ESTIMATORS = {
    "naive_average": naive_average_mass_and_charge,
    "neutral_average": neutral_average_mass_and_charge,
    "lower_median": lower_median_mass_and_charge,
}
RT_ESTIMATORS = {
    "median": median_rt,
    "mass_lower_median": lower_median_mass_rt,
}


def resolve_gap_estimators(config: GapAverageConfig):
    """(pepmass_fn, rt_fn) for a GapAverageConfig, including the coupled rule
    that lower_median pepmass forces the lower-median-mass member's RT
    (ref src/average_spectrum_clustering.py:190-191).  Shared by the numpy
    and TPU drivers so the override lives in exactly one place."""
    rt_mode = config.rt
    if config.pepmass == "lower_median":
        rt_mode = "mass_lower_median"
    return PEPMASS_ESTIMATORS[config.pepmass], RT_ESTIMATORS[rt_mode]


# ---------------------------------------------------------------------------
# C4: medoid representative
# (ref src/most_similar_representative.py:13-19,87-111)
# ---------------------------------------------------------------------------

def xcorr_prescore(s1: Spectrum, s2: Spectrum, bin_size: float = 0.1) -> float:
    """Occupancy-grid binned dot product normalised by the smaller raw peak
    count — the capability of OpenMS ``XQuestScores::xCorrelationPrescore``
    consumed at ref src/most_similar_representative.py:15 ("simple, binned
    dot product, normalized by number of peaks", ref :11).  Bin index is
    ``floor(mz / bin_size)``; each occupied bin contributes 1 regardless of
    how many peaks fall in it.  Empty spectra score 0.
    """
    if s1.n_peaks == 0 or s2.n_peaks == 0:
        return 0.0
    b1 = np.unique((s1.mz / bin_size).astype(np.int64))
    b2 = np.unique((s2.mz / bin_size).astype(np.int64))
    shared = np.intersect1d(b1, b2, assume_unique=True).size
    return float(shared) / min(s1.n_peaks, s2.n_peaks)


def xcorr_distance(s1: Spectrum, s2: Spectrum, bin_size: float = 0.1) -> float:
    """1 - xcorr (ref src/most_similar_representative.py:13-16)."""
    return 1.0 - xcorr_prescore(s1, s2, bin_size)


def medoid_index(
    members: list[Spectrum], config: MedoidConfig = MedoidConfig()
) -> int:
    """Index of the member with minimal total distance to all others.

    Total-distance semantics reproduced from ref
    src/most_similar_representative.py:88-110: the reference fills an upper
    triangular matrix INCLUDING the diagonal and sums row i + column i, so
    the self-distance D[i,i] counts twice; ties break to the lowest index
    (ref :103-110).  Singleton clusters return index 0 (ref :79-81).
    """
    n = len(members)
    if n == 0:
        raise ValueError("empty cluster")
    if n == 1:
        return 0
    dist = np.zeros((n, n))
    for i in range(n):
        for j in range(i, n):
            dist[i, j] = xcorr_distance(members[i], members[j], config.bin_size)
    sym = dist + dist.T  # row_i + col_i of the triangular fill, diag twice
    total = sym.sum(axis=1) / n
    return int(np.argmin(total))  # np.argmin: first (lowest-index) minimum


# ---------------------------------------------------------------------------
# C3: best-spectrum representative (ref src/best_spectrum.py:67-100)
# ---------------------------------------------------------------------------

def _normalize_usi(usi: str) -> str:
    """Collapse empty USI fields and drop any interpretation suffix so the
    scores join matches on (collection, run, scan).

    The reference builds score USIs with a double colon
    (``...raw::scan:N``, ref src/best_spectrum.py:61-62) while its own
    converter emits single-colon USIs (ref src/convert_mgf_cluster.py:15) —
    making the join silently empty, a latent reference bug we fix by
    normalising both sides here.
    """
    parts = [p for p in usi.split(":") if p != ""]
    if "scan" in parts:
        k = parts.index("scan")
        parts = parts[: k + 2]  # drop :PEPTIDE/z interpretation suffix
    return ":".join(parts)


def best_spectrum_index(
    members: list[Spectrum],
    scores: dict[str, float],
    config: BestSpectrumConfig = BestSpectrumConfig(),
) -> int:
    """Index of the member with the highest PSM score.

    Raises ValueError when no member has a score (ref src/best_spectrum.py:
    98-99; callers drop such clusters — ref :170-174).  Tie-break: the
    lexicographically smallest USI among the tied maxima, matching pandas
    ``idxmax`` over the USI-sorted series built at ref :64.  USIs are
    normalised on both sides (see ``_normalize_usi``).
    """
    norm_scores = {_normalize_usi(k): v for k, v in scores.items()}
    best_i: int | None = None
    best: tuple[float, str] | None = None
    for i, s in enumerate(members):
        usi = _normalize_usi(s.usi)
        if usi not in norm_scores:
            continue
        key = (-norm_scores[usi], usi)
        if best is None or key < best:
            best = key
            best_i = i
    if best_i is None:
        raise ValueError("No scores found for the given scan numbers")
    return best_i


# ---------------------------------------------------------------------------
# C5: binned-cosine quality metric (ref src/benchmark.py:11-38)
# ---------------------------------------------------------------------------

def binned_cosine(
    a: Spectrum, b: Spectrum, config: CosineConfig = CosineConfig()
) -> float:
    """Cosine similarity of two spectra on a shared ~0.005 Da grid.

    Grid semantics reproduced from ref src/benchmark.py:11-29: bin edges
    ``arange(-mz_space/2, max_mz, mz_space)`` where max_mz is the larger LAST
    m/z of the pair (assumes sorted peaks, ref :20); peaks at or beyond the
    last edge are excluded, as scipy ``binned_statistic`` does.  Despite the
    reference's name ``cos_dist`` this is a similarity; zero-norm inputs
    score 0 (ref :26-27).
    """
    if a.n_peaks == 0 or b.n_peaks == 0:
        return 0.0
    space = config.mz_space
    max_mz = max(a.mz[-1], b.mz[-1])
    edges = np.arange(-space / 2.0, max_mz, space)
    if edges.size < 2:
        return 0.0

    def binned(s: Spectrum) -> np.ndarray:
        vec = np.zeros(edges.size - 1)
        idx = np.floor((s.mz - edges[0]) / space).astype(np.int64)
        ok = (s.mz >= edges[0]) & (s.mz <= edges[-1])
        # scipy binned_statistic puts values equal to the last edge into the
        # final bin (right-closed last bin)
        idx = np.where(idx == edges.size - 1, edges.size - 2, idx)
        # optional sqrt/log intensity transform (BASELINE configs[3]),
        # shared with the device/native paths via ops.quantize
        weights = quantize.cosine_normalize(s.intensity, config)
        np.add.at(vec, idx[ok], weights[ok])
        return vec

    va, vb = binned(a), binned(b)
    na, nb = float(va @ va), float(vb @ vb)
    if na == 0.0 or nb == 0.0:
        return 0.0
    return float(va @ vb) / np.sqrt(na * nb)


def average_cosine(
    representative: Spectrum,
    members: list[Spectrum],
    config: CosineConfig = CosineConfig(),
) -> float:
    """Mean binned cosine of a representative to the cluster members
    (ref src/benchmark.py:31-38); empty member list scores 0."""
    if not members:
        return 0.0
    return float(
        np.mean([binned_cosine(representative, m, config) for m in members])
    )


# ---------------------------------------------------------------------------
# Cluster-level drivers
# ---------------------------------------------------------------------------

# module-level registry: the oracle backend is a module, not a class, so its
# telemetry lives here.  It records the SAME metric families the device
# backend does (device-only series — compiles, H2D/D2H bytes, padding —
# simply stay zero), so an oracle run's --metrics-out and run_end.device
# diff cleanly against a device run's.  The method-level tracing spans
# below likewise share names with TpuBackend's (labeled backend="numpy"
# vs "tpu"), so oracle and device traces diff cleanly too.
from specpride_tpu.observability import MetricsRegistry as _MetricsRegistry
from specpride_tpu.observability import tracing

metrics = _MetricsRegistry()


def _count_run(method: str, n: int) -> None:
    faults.check("dispatch")
    metrics.counter(
        "specpride_oracle_clusters_total",
        "clusters processed by the numpy oracle", labels=("method",),
    ).inc(n, method=method)


def prepare_chunk(method, clusters, config, cos_config=None, stats=None):
    """Two-phase chunk protocol, oracle side: the numpy backend has no
    pack stage — every ``run_*`` below is a per-cluster loop with no
    device inputs to build — so phase 1 is always empty and the pipelined
    CLI executor falls back to the one-shot path.  It still wins on
    streamed inputs: chunk MATERIALIZATION (the MGF window parse) runs on
    the pack lane either way — and the pack worker pool may call this
    from several threads at once, which is trivially safe here (no state
    is touched; the per-worker ``stats`` is private by contract).
    Mirrors ``TpuBackend.prepare_chunk`` so callers can duck-type both
    backends."""
    return None


def supports_prepare(method) -> bool:
    """The other half of the duck-typed protocol (see
    ``TpuBackend.supports_prepare``): never — so the executor keeps the
    oracle's historical single-chunk execution instead of forcing
    checkpoint-interval chunking for zero overlap gain."""
    return False


@tracing.traced("method:bin_mean", backend="numpy")
def run_bin_mean(clusters: list[Cluster], config: BinMeanConfig = BinMeanConfig()) -> list[Spectrum]:
    """Per-cluster loop of ref src/binning.py:291-297."""
    _count_run("bin_mean", len(clusters))
    return [bin_mean_consensus(c.members, config, c.cluster_id) for c in clusters]


@tracing.traced("method:gap_average", backend="numpy")
def run_gap_average(
    clusters: list[Cluster], config: GapAverageConfig = GapAverageConfig()
) -> list[Spectrum]:
    """Per-cluster loop of ref src/average_spectrum_clustering.py:158-164."""
    _count_run("gap_average", len(clusters))
    get_pepmass, get_rt = resolve_gap_estimators(config)
    out = []
    for c in clusters:
        mz, z = get_pepmass(c.members)
        rt = get_rt(c.members)
        out.append(
            gap_average_consensus(c.members, config, c.cluster_id, mz, z, rt)
        )
    return out


@tracing.traced("method:medoid", backend="numpy")
def run_medoid(
    clusters: list[Cluster], config: MedoidConfig = MedoidConfig()
) -> list[Spectrum]:
    """Per-cluster loop of ref src/most_similar_representative.py:60-111."""
    _count_run("medoid", len(clusters))
    return [c.members[medoid_index(c.members, config)] for c in clusters]


@tracing.traced("method:best", backend="numpy")
def run_best_spectrum(
    clusters: list[Cluster],
    scores: dict[str, float],
    config: BestSpectrumConfig = BestSpectrumConfig(),
) -> list[Spectrum]:
    """Scoreless clusters are silently dropped (ref src/best_spectrum.py:
    170-174)."""
    _count_run("best", len(clusters))
    out = []
    for c in clusters:
        try:
            out.append(c.members[best_spectrum_index(c.members, scores, config)])
        except ValueError:
            pass
    return out
