"""Checker registry + the ``specpride lint`` driver.

``run_checks`` is the library entry (tests drive fixtures through it);
``main`` implements the CLI verb: per-check selection, ``--list``,
``--json`` reports, inline-suppression filtering, and the committed
baseline gate (exit 1 only on NEW, unbaselined findings).
"""

from __future__ import annotations

import json
import os
import sys

from specpride_tpu.analysis import (
    cli_flags,
    fault_sites,
    jit_hygiene,
    journal_schema,
    lane_safety,
    metrics_conformance,
)
from specpride_tpu.analysis.baseline import BASELINE_NAME, Baseline
from specpride_tpu.analysis.core import Finding, Project

REPORT_VERSION = 1

# id -> (one-line description, run fn).  Order is render order.
CHECKERS: dict[str, tuple] = {
    lane_safety.CHECK: (
        "attributes mutated from >= 2 lanes must sit in a "
        "lock-protected region (call-graph lane inference)",
        lane_safety.run,
    ),
    jit_hygiene.CHECK: (
        "jit statics mirrored into warmup-registry builders, donation "
        "twins via jit_pair, no host syncs in jitted bodies",
        jit_hygiene.run,
    ),
    journal_schema.CHECK: (
        "EVENT_FIELDS vs emit sites vs the docs event table vs "
        "renderer literals, in both directions",
        journal_schema.run,
    ),
    metrics_conformance.CHECK: (
        "metric names vs the strict exposition grammar, the docs "
        "catalog, and the pre-register-at-0 contract",
        metrics_conformance.run,
    ),
    cli_flags.CHECK: (
        "DAEMON_ONLY_FLAGS vs the parser and its dest mirror; every "
        "flag documented under docs/",
        cli_flags.run,
    ),
    fault_sites.CHECK: (
        "FAULT_SITES vs actual check() visit sites, in both directions",
        fault_sites.run,
    ),
}


def checker_ids() -> list[str]:
    return list(CHECKERS)


def run_checks(
    root: str, select: list[str] | None = None,
    project: Project | None = None,
) -> list[Finding]:
    """All (selected) checkers over ``root``, inline suppressions
    applied, sorted for stable output."""
    project = project or Project(root)
    findings: list[Finding] = []
    for check_id, (_desc, fn) in CHECKERS.items():
        if select and check_id not in select:
            continue
        findings.extend(fn(project))
    by_rel = {m.rel: m for m in project.modules}
    kept = []
    for f in findings:
        mod = by_rel.get(f.path)
        if mod is not None and f.line and (
            f.check in mod.suppressed_at(f.line)
            or "*" in mod.suppressed_at(f.line)
        ):
            continue
        kept.append(f)
    kept.sort(key=Finding.sort_key)
    return kept


def _report(
    root: str, select, findings: list[Finding], baseline: Baseline,
    new, baselined, stale, bad,
) -> dict:
    return {
        "version": REPORT_VERSION,
        "root": os.path.abspath(root),
        "checks": [
            {"id": cid, "description": desc}
            for cid, (desc, _fn) in CHECKERS.items()
            if not select or cid in select
        ],
        "findings": [f.to_json() for f in new],
        "baselined": [f.to_json() for f in baselined],
        "baseline": {
            "path": baseline.path,
            "entries": len(baseline.entries),
            "stale": stale,
            "missing_reason": bad,
        },
        "summary": {
            "total": len(findings),
            "new": len(new),
            "baselined": len(baselined),
            "stale_baseline_entries": len(stale),
            "baseline_entries_missing_reason": len(bad),
        },
    }


def main(args) -> int:
    if args.list:
        for cid, (desc, _fn) in CHECKERS.items():
            print(f"{cid:22s} {desc}")
        return 0
    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = sorted(set(select) - set(CHECKERS))
        if unknown:
            print(
                f"lint: unknown checker(s) {unknown}; known: "
                f"{', '.join(CHECKERS)}", file=sys.stderr,
            )
            return 2
    root = os.path.abspath(args.root)
    project = Project(root)
    for err in project.errors:
        print(f"lint: {err}", file=sys.stderr)
    findings = run_checks(root, select=select, project=project)

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.update_baseline:
        Baseline.write(
            baseline_path, findings,
            existing=Baseline.load(baseline_path), select=select,
        )
        print(
            f"lint: wrote {len(findings)} suppression(s) to "
            f"{baseline_path} — fill in every empty 'reason' before "
            f"committing"
        )
        return 0
    baseline = (
        Baseline([], path=None) if args.no_baseline
        else Baseline.load(baseline_path)
    )
    new, baselined, stale, bad = baseline.split(findings, select=select)

    report = _report(
        root, select, findings, baseline, new, baselined, stale, bad
    )
    if args.json:
        payload = json.dumps(report, indent=1) + "\n"
        if args.json == "-":
            sys.stdout.write(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload)
    else:
        for f in new:
            loc = f"{f.path}:{f.line}" if f.line else f.path
            print(f"{loc}: [{f.check}] {f.message}")
        if baselined:
            print(f"lint: {len(baselined)} baselined finding(s)")
        for e in stale:
            print(
                f"lint: stale baseline entry {e.get('check')}:"
                f"{e.get('path')}:{e.get('symbol')} — remove it"
            )
    for e in bad:
        print(
            f"lint: baseline entry {e.get('check')}:{e.get('path')}:"
            f"{e.get('symbol')} has no justification 'reason'",
            file=sys.stderr,
        )
    if project.errors:
        return 2
    if new or bad:
        if new and not args.json:
            print(
                f"lint: {len(new)} new finding(s) — fix, suppress "
                f"inline (`# lint: ok[<check>] why`), or baseline "
                f"with --update-baseline + a written reason"
            )
        return 1
    if not args.json:
        print(
            f"lint OK: {len(CHECKERS) if not select else len(select)} "
            f"checker(s), 0 new finding(s), "
            f"{len(baselined)} baselined"
        )
    return 0
