"""``journal-schema``: the journal event schema, its emit sites, the
docs event table, and every renderer literal must agree.

Anchors (convention-discovered):

* ``EVENT_FIELDS`` — the authoritative ``{event: frozenset(required)}``
  module-level table (``observability/journal.py``).
* emit sites — every ``<journal>.emit(...)`` call whose first argument
  is a literal event name.
* the docs event table — the markdown table in
  ``docs/observability.md`` whose header's first cell is ``event``
  (payload cell: required fields before a ``plus`` marker).
* renderer literals — any comparison of ``x["event"]`` /
  ``x.get("event")`` against string constants anywhere in the project
  (``stats_cli``, audits, exporters).

Checked, in both directions: emitted events exist in the schema and
carry every required field (when the kwargs are statically visible and
no ``**`` passthrough hides them); schema events are documented with
exactly the schema's required payload; documented events exist in the
schema; renderer literals name real events.

The v4 trace-context envelope self-enforces through the same anchors:
``TRACE_EVENT_FIELDS`` (same module as ``EVENT_FIELDS``) names the
events that must carry their causal fields (``trace_id`` on the serving
job events, ``trace_ids`` on ``batch_dispatch``) — every statically
visible emit site must pass them, and the docs event-table row must at
least mention each one (required or behind the ``plus`` marker), so a
new emit site cannot silently ship an untraceable event.

``V5_EVENT_FIELDS`` (the v5 additions — ``chunk_s`` on ``heartbeat``)
gets the same both-direction treatment: every statically visible emit
site must pass the field, and the docs row must mention it.  Version-
gated tables keep old committed journals valid while making it
impossible for NEW emit sites to drop the field the autotune signal
fold depends on.

``V6_EVENT_FIELDS`` (the v6 additions — ``incident_id`` + ``evidence``
on the flight recorder's ``incident`` event) follows the identical
discipline: ``incident-replay`` re-derives firings from exactly these
fields, so an emit site dropping them would ship an unauditable
incident.
"""

from __future__ import annotations

import ast

from specpride_tpu.analysis.core import (
    Finding,
    Project,
    dict_of_str_sets,
    has_starstar,
    parse_event_table,
    str_const,
)

CHECK = "journal-schema"

_DOC = "docs/observability.md"


def _event_fields(project: Project):
    hit = project.one_constant("EVENT_FIELDS")
    if hit is None:
        return None
    mod, node, line = hit
    table = dict_of_str_sets(node)
    if table is None:
        return None
    return mod, {k: v for k, v in table.items() if v is not None}, line


def _trace_event_fields(project: Project) -> dict[str, set]:
    """The v4 trace-envelope table (``TRACE_EVENT_FIELDS``), or empty
    when the project doesn't declare one (pre-v4 fixture trees)."""
    hit = project.one_constant("TRACE_EVENT_FIELDS")
    if hit is None:
        return {}
    _mod, node, _line = hit
    table = dict_of_str_sets(node)
    if table is None:
        return {}
    return {k: v for k, v in table.items() if v is not None}


def _v5_event_fields(project: Project) -> dict[str, set]:
    """The v5 additive-field table (``V5_EVENT_FIELDS``), or empty when
    the project doesn't declare one (pre-v5 fixture trees)."""
    hit = project.one_constant("V5_EVENT_FIELDS")
    if hit is None:
        return {}
    _mod, node, _line = hit
    table = dict_of_str_sets(node)
    if table is None:
        return {}
    return {k: v for k, v in table.items() if v is not None}


def _v6_event_fields(project: Project) -> dict[str, set]:
    """The v6 additive-field table (``V6_EVENT_FIELDS``), or empty when
    the project doesn't declare one (pre-v6 fixture trees)."""
    hit = project.one_constant("V6_EVENT_FIELDS")
    if hit is None:
        return {}
    _mod, node, _line = hit
    table = dict_of_str_sets(node)
    if table is None:
        return {}
    return {k: v for k, v in table.items() if v is not None}


def _emit_sites(project: Project):
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit"
                and node.args
            ):
                continue
            event = str_const(node.args[0])
            if event is None:
                continue
            kwargs = {kw.arg for kw in node.keywords if kw.arg}
            yield mod, node, event, kwargs, has_starstar(node)


def _event_literal_comparisons(project: Project):
    """String constants compared against ``x["event"]`` /
    ``x.get("event")`` anywhere in the project."""

    def is_event_access(n) -> bool:
        if isinstance(n, ast.Subscript):
            return str_const(n.slice) == "event"
        if isinstance(n, ast.Call) and isinstance(
            n.func, ast.Attribute
        ) and n.func.attr == "get" and n.args:
            return str_const(n.args[0]) == "event"
        return False

    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            if not any(is_event_access(s) for s in sides):
                continue
            for s in sides:
                lit = str_const(s)
                if lit is not None:
                    yield mod, node.lineno, lit
                for elt in getattr(s, "elts", []):
                    lit = str_const(elt)
                    if lit is not None:
                        yield mod, node.lineno, lit


def run(project: Project) -> list[Finding]:
    anchor = _event_fields(project)
    if anchor is None:
        return []
    schema_mod, schema, schema_line = anchor
    trace_fields = _trace_event_fields(project)
    v5_fields = _v5_event_fields(project)
    v6_fields = _v6_event_fields(project)
    findings: list[Finding] = []

    # 1. emit sites vs schema (incl. the v4 trace envelope)
    for mod, node, event, kwargs, passthrough in _emit_sites(project):
        if event not in schema:
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=node.lineno,
                symbol=f"emit:{event}",
                message=(
                    f"emitted event `{event}` is not in EVENT_FIELDS"
                ),
            ))
            continue
        if passthrough:
            continue  # **fields forwarding: kwargs not statically visible
        missing = sorted(schema[event] - kwargs)
        if missing:
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=node.lineno,
                symbol=f"emit:{event}:fields",
                message=(
                    f"emit of `{event}` is missing required fields "
                    f"{missing} (EVENT_FIELDS)"
                ),
            ))
        missing_trace = sorted(
            trace_fields.get(event, set()) - kwargs
        )
        if missing_trace:
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=node.lineno,
                symbol=f"emit:{event}:trace",
                message=(
                    f"emit of `{event}` is missing the v4 trace-"
                    f"envelope fields {missing_trace} "
                    f"(TRACE_EVENT_FIELDS) — an untraceable serving "
                    f"event breaks the cross-process causal join"
                ),
            ))
        missing_v5 = sorted(v5_fields.get(event, set()) - kwargs)
        if missing_v5:
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=node.lineno,
                symbol=f"emit:{event}:v5",
                message=(
                    f"emit of `{event}` is missing the v5 fields "
                    f"{missing_v5} (V5_EVENT_FIELDS) — the autotune "
                    f"signal fold depends on them"
                ),
            ))
        missing_v6 = sorted(v6_fields.get(event, set()) - kwargs)
        if missing_v6:
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=node.lineno,
                symbol=f"emit:{event}:v6",
                message=(
                    f"emit of `{event}` is missing the v6 fields "
                    f"{missing_v6} (V6_EVENT_FIELDS) — incident-replay "
                    f"re-derives firings from them"
                ),
            ))

    # 2. docs table vs schema, both directions + payload equality
    doc_text = project.doc(_DOC)
    if doc_text is not None:
        table = parse_event_table(doc_text)
        if table:
            for event, fields in sorted(schema.items()):
                row = table.get(event)
                if row is None:
                    findings.append(Finding(
                        check=CHECK, path=_DOC, line=0,
                        symbol=f"doc:{event}",
                        message=(
                            f"event `{event}` is in EVENT_FIELDS but "
                            f"has no row in the {_DOC} event table"
                        ),
                    ))
                    continue
                if row["required"] != fields:
                    missing = sorted(fields - row["required"])
                    extra = sorted(row["required"] - fields)
                    detail = []
                    if missing:
                        detail.append(f"missing {missing}")
                    if extra:
                        detail.append(
                            f"lists non-required {extra} (move behind "
                            f"a `plus` marker if optional)"
                        )
                    findings.append(Finding(
                        check=CHECK, path=_DOC, line=row["line"],
                        symbol=f"doc:{event}:fields",
                        message=(
                            f"{_DOC} row for `{event}` disagrees with "
                            f"EVENT_FIELDS: {'; '.join(detail)}"
                        ),
                    ))
            for event, row in sorted(table.items()):
                if event not in schema:
                    findings.append(Finding(
                        check=CHECK, path=_DOC, line=row["line"],
                        symbol=f"doc:{event}:unknown",
                        message=(
                            f"{_DOC} documents event `{event}` which "
                            f"is not in EVENT_FIELDS"
                        ),
                    ))
            # v4 trace envelope: the documented row must at least
            # MENTION each causal field (required, or optional behind
            # the `plus` marker — they are version-gated, so either
            # placement is legitimate; silence is not)
            for event, fields in sorted(trace_fields.items()):
                row = table.get(event)
                if row is None:
                    continue  # the missing-row finding above covers it
                absent = sorted(fields - row.get("mentioned", set()))
                if absent:
                    findings.append(Finding(
                        check=CHECK, path=_DOC, line=row["line"],
                        symbol=f"doc:{event}:trace",
                        message=(
                            f"{_DOC} row for `{event}` does not "
                            f"mention the v4 trace-envelope fields "
                            f"{absent} (TRACE_EVENT_FIELDS)"
                        ),
                    ))
            # v5 additive fields: same mention rule as the v4 envelope
            for event, fields in sorted(v5_fields.items()):
                row = table.get(event)
                if row is None:
                    continue  # the missing-row finding above covers it
                absent = sorted(fields - row.get("mentioned", set()))
                if absent:
                    findings.append(Finding(
                        check=CHECK, path=_DOC, line=row["line"],
                        symbol=f"doc:{event}:v5",
                        message=(
                            f"{_DOC} row for `{event}` does not "
                            f"mention the v5 fields {absent} "
                            f"(V5_EVENT_FIELDS)"
                        ),
                    ))
            # v6 additive fields: same mention rule as the v4 envelope
            for event, fields in sorted(v6_fields.items()):
                row = table.get(event)
                if row is None:
                    continue  # the missing-row finding above covers it
                absent = sorted(fields - row.get("mentioned", set()))
                if absent:
                    findings.append(Finding(
                        check=CHECK, path=_DOC, line=row["line"],
                        symbol=f"doc:{event}:v6",
                        message=(
                            f"{_DOC} row for `{event}` does not "
                            f"mention the v6 fields {absent} "
                            f"(V6_EVENT_FIELDS)"
                        ),
                    ))

    # 3. renderer literals vs schema
    for mod, line, lit in _event_literal_comparisons(project):
        if lit not in schema:
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=line,
                symbol=f"render:{lit}",
                message=(
                    f"event literal `{lit}` compared against "
                    f"x[\"event\"] is not in EVENT_FIELDS — stale "
                    f"renderer or typo"
                ),
            ))
    return findings
