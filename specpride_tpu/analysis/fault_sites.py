"""``fault-sites``: the declared fault-site tables vs the visit sites
actually compiled into the lanes.

Anchors: the module declaring ``FAULT_SITES`` (and the executor subset
``EXECUTOR_FAULT_SITES``), plus every ``<faults>.check("site")`` call
whose receiver resolves to an import of that module (or a bare
``check`` imported from it).

Rules, both directions:

1. every declared site has at least one literal visit call site — a
   site nobody visits makes ``--inject-faults site:...`` silently inert
   and the chaos CI matrix vacuous;
2. every literal site passed to a faults check is declared — a typo'd
   site would never fire.
"""

from __future__ import annotations

import ast

from specpride_tpu.analysis.core import (
    Finding,
    Project,
    str_const,
    str_seq_resolved,
)

CHECK = "fault-sites"


def _declared(project: Project):
    hit = project.one_constant("FAULT_SITES")
    if hit is None:
        return None
    mod, node, line = hit
    env = {}
    for name in ("EXECUTOR_FAULT_SITES",):
        sub = project.one_constant(name)
        if sub is not None:
            _m, sub_node, _l = sub
            seq = str_seq_resolved(sub_node, {})
            if seq is not None:
                env[name] = seq
    sites = str_seq_resolved(node, env)
    if sites is None:
        return None
    return mod, list(sites), line


def _faults_aliases(project: Project, faults_mod_name: str):
    """Per-module local names bound to the faults module (import
    aliases) and to its ``check`` function (from-imports)."""
    mod_aliases: dict[str, set] = {}
    fn_aliases: dict[str, set] = {}
    for mod in project.modules:
        mods: set = set()
        fns: set = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == faults_mod_name:
                        mods.add(a.asname or a.name.split(".")[-1])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    if full == faults_mod_name:
                        mods.add(a.asname or a.name)
                    elif node.module == faults_mod_name and (
                        a.name == "check"
                    ):
                        fns.add(a.asname or a.name)
        mod_aliases[mod.name] = mods
        fn_aliases[mod.name] = fns
    return mod_aliases, fn_aliases


def run(project: Project) -> list[Finding]:
    decl = _declared(project)
    if decl is None:
        return []
    faults_mod, sites, decl_line = decl
    mod_aliases, fn_aliases = _faults_aliases(project, faults_mod.name)

    visited: dict[str, list] = {}
    findings: list[Finding] = []
    for mod in project.modules:
        if mod.name == faults_mod.name:
            continue  # the plan's own internals are not visit sites
        aliases = mod_aliases.get(mod.name, set())
        fns = fn_aliases.get(mod.name, set())
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            is_visit = (
                isinstance(f, ast.Attribute)
                and f.attr == "check"
                and isinstance(f.value, ast.Name)
                and f.value.id in aliases
            ) or (isinstance(f, ast.Name) and f.id in fns)
            if not is_visit:
                continue
            site = str_const(node.args[0])
            if site is None:
                continue
            visited.setdefault(site, []).append((mod, node.lineno))
            if site not in sites:
                findings.append(Finding(
                    check=CHECK, path=mod.rel, line=node.lineno,
                    symbol=f"{site}:undeclared",
                    message=(
                        f"fault visit site `{site}` is not declared "
                        f"in FAULT_SITES — an injected fault there "
                        f"could never be armed"
                    ),
                ))
    for site in sites:
        if site not in visited:
            findings.append(Finding(
                check=CHECK, path=faults_mod.rel, line=decl_line,
                symbol=f"{site}:unvisited",
                message=(
                    f"FAULT_SITES declares `{site}` but no lane ever "
                    f"visits it (`check(\"{site}\")`) — injection "
                    f"specs naming it are silently inert"
                ),
            ))
    return findings
