"""Project-invariant static analysis (``specpride lint``).

Thirteen PRs of cross-cutting contracts — jit statics mirrored into
shape keys and warmup builders, lane-shared state behind locks, journal
events / metric names / CLI flags kept in sync with ``docs/`` and their
renderers — were enforced only by convention and review.  This package
enforces them by machine at the SOURCE level: an AST + cross-artifact
analyzer with one checker per invariant family, a committed baseline
for legacy findings, and a CI gate (``scripts/ci.sh``) that fails on
any new finding.

Checkers (``specpride lint --list``):

* ``lane-safety`` — call-graph lane inference from the thread entry
  points; flags attributes mutated from >= 2 lanes without a lock.
* ``jit-hygiene`` — jit statics vs warmup-registry builders, donation
  twins via ``jit_pair``, no host syncs inside jitted bodies.
* ``journal-schema`` — ``EVENT_FIELDS`` vs emit sites vs the
  ``docs/observability.md`` event table vs renderer literals.
* ``metrics-conformance`` — registered metric names vs the strict
  exposition grammar, the docs catalog, and pre-register-at-0.
* ``cli-flags`` — ``DAEMON_ONLY_FLAGS`` vs the parser, and every flag
  documented under ``docs/``.
* ``fault-sites`` — ``FAULT_SITES`` vs actual harness visit sites.

See ``docs/static-analysis.md`` for the full catalog, known limits and
suppression syntax.
"""

from specpride_tpu.analysis.core import Finding, Project
from specpride_tpu.analysis.runner import (
    CHECKERS,
    checker_ids,
    run_checks,
)

__all__ = [
    "CHECKERS",
    "Finding",
    "Project",
    "checker_ids",
    "run_checks",
]
