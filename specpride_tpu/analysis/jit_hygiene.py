"""``jit-hygiene``: device-kernel construction invariants.

Four rules, all rooted in real regressions:

1. every device kernel is built as a donation twin pair via
   ``jit_pair`` (bare ``jax.jit`` in an ops module has no ``--no-
   donate`` escape hatch and no warmup twin selection);
2. every ``jit_pair`` kernel has a warmup-registry builder (the
   ``_BUILDERS`` table) that references it — a kernel absent from the
   registry silently re-compiles on every warmed rerun;
3. each registry builder's static kwargs must exactly equal the
   kernel's ``static_argnames`` — the PR 6 ``cosine_flat`` bug class: a
   static missing from the builder (or the shape key it decodes) warms
   the WRONG executable;
4. no host syncs inside jitted bodies: ``float(...)``, ``.item()``,
   ``np.asarray``/``np.array``/``jax.device_get`` force a device
   round-trip per trace and break async dispatch.
"""

from __future__ import annotations

import ast

from specpride_tpu.analysis.core import (
    Finding,
    Project,
    call_name,
    str_seq,
)

CHECK = "jit-hygiene"

_HOST_SYNC_NP = {"asarray", "array", "device_get"}


class _JitKernel:
    def __init__(self, module, name: str, donated: str | None,
                 statics: tuple, line: int, fn_name: str | None):
        self.module = module
        self.name = name
        self.donated = donated
        self.statics = statics
        self.line = line
        self.fn_name = fn_name  # underlying python fn, when a Name


def _collect_jit_pairs(project: Project) -> list[_JitKernel]:
    out = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and call_name(node.value) == "jit_pair"
            ):
                continue
            call = node.value
            statics_node = None
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    statics_node = kw.value
            if statics_node is None and len(call.args) >= 2:
                statics_node = call.args[1]
            statics = tuple(str_seq(statics_node) or ())
            fn_name = None
            if call.args and isinstance(call.args[0], ast.Name):
                fn_name = call.args[0].id
            tgt = node.targets[0]
            if isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2 and all(
                isinstance(e, ast.Name) for e in tgt.elts
            ):
                plain, donated = tgt.elts[0].id, tgt.elts[1].id
            elif isinstance(tgt, ast.Name):
                plain, donated = tgt.id, None
            else:
                continue
            out.append(_JitKernel(
                mod, plain, donated, statics, node.lineno, fn_name
            ))
    return out


def _jitted_function_defs(project: Project, kernels) -> list:
    """(module, FunctionDef) for every function that runs under jit:
    the underlying fns of jit_pair kernels plus anything decorated with
    ``jax.jit`` / ``partial(jax.jit, ...)``."""
    by_mod_fn = {}
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_mod_fn.setdefault(mod.name, {})[node.name] = (
                    mod, node
                )
    out = []
    seen = set()
    for k in kernels:
        if k.fn_name:
            hit = by_mod_fn.get(k.module.name, {}).get(k.fn_name)
            if hit and id(hit[1]) not in seen:
                seen.add(id(hit[1]))
                out.append(hit)
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for dec in node.decorator_list:
                src = ast.unparse(dec)
                if "jax.jit" in src or src == "jit" or src.startswith(
                    "jit("
                ):
                    if id(node) not in seen:
                        seen.add(id(node))
                        out.append((mod, node))
    return out


def _host_sync_findings(project: Project, kernels) -> list[Finding]:
    findings = []
    for mod, fn in _jitted_function_defs(project, kernels):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            bad = None
            if isinstance(f, ast.Name) and f.id == "float" and (
                node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                bad = "float(...)"
            elif isinstance(f, ast.Attribute) and f.attr == "item":
                bad = ".item()"
            elif (
                isinstance(f, ast.Attribute)
                and f.attr in _HOST_SYNC_NP
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy", "onp", "jax")
            ):
                bad = f"{f.value.id}.{f.attr}(...)"
            if bad:
                findings.append(Finding(
                    check=CHECK, path=mod.rel, line=node.lineno,
                    symbol=f"{fn.name}:host-sync",
                    message=(
                        f"host sync `{bad}` inside jitted body "
                        f"`{fn.name}` — forces a device round-trip "
                        f"per trace"
                    ),
                ))
    return findings


def _bare_jit_findings(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        if ".ops." not in f".{mod.name}." or mod.name.endswith(
            "jit_util"
        ):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr == "jit" and isinstance(
                node.func.value, ast.Name
            ) and node.func.value.id == "jax":
                findings.append(Finding(
                    check=CHECK, path=mod.rel, line=node.lineno,
                    symbol="bare-jax-jit",
                    message=(
                        "bare `jax.jit` in an ops module — build the "
                        "kernel with `jit_pair` so it has a donation "
                        "twin and the warmup registry can select it"
                    ),
                ))
    return findings


def _builder_map(project: Project):
    """Parse the warmup registry: ``_BUILDERS`` keys -> the builder
    function def each resolves to (through one lambda hop)."""
    hit = project.one_constant("_BUILDERS")
    if hit is None:
        return None
    mod, node, _line = hit
    if not isinstance(node, ast.Dict):
        return None
    mod_fns = {
        n.name: n for n in ast.walk(mod.tree)
        if isinstance(n, ast.FunctionDef)
    }
    out = {}
    for k, v in zip(node.keys, node.values):
        key = k.value if isinstance(k, ast.Constant) else None
        if not isinstance(key, str):
            continue
        target = None
        if isinstance(v, ast.Name):
            target = mod_fns.get(v.id)
        elif isinstance(v, ast.Lambda) and isinstance(
            v.body, ast.Call
        ) and isinstance(v.body.func, ast.Name):
            target = mod_fns.get(v.body.func.id)
        out[key] = (target, v.lineno if hasattr(v, "lineno") else 0)
    return mod, out


def _builder_refs_and_statics(mod_fns: dict, fn: ast.FunctionDef,
                              kernel_names: set,
                              _depth: int = 0) -> tuple[set, set]:
    """Kernel names a builder references, and the static kwarg keys of
    its ``dict(...)`` statics literal (following one helper-call hop)."""
    refs: set = set()
    statics: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        else:
            continue
        base = (
            name[: -len("_donated")] if name.endswith("_donated")
            else name
        )
        if base in kernel_names:
            refs.add(base)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Name
        ) and node.func.id == "dict":
            keys = {kw.arg for kw in node.keywords if kw.arg}
            if keys:
                statics |= keys
    if (not refs or not statics) and _depth < 2:
        # helper hop: `core, finalize = _medoid_args(...)` style builders
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Name
            ) and node.func.id in mod_fns and node.func.id != fn.name:
                r2, s2 = _builder_refs_and_statics(
                    mod_fns, mod_fns[node.func.id], kernel_names,
                    _depth + 1,
                )
                refs |= r2
                if not statics:
                    statics |= s2
    return refs, statics


def run(project: Project) -> list[Finding]:
    kernels = _collect_jit_pairs(project)
    findings = _bare_jit_findings(project)
    findings += _host_sync_findings(project, kernels)

    for k in kernels:
        if k.donated is None or k.donated != f"{k.name}_donated":
            findings.append(Finding(
                check=CHECK, path=k.module.rel, line=k.line,
                symbol=f"{k.name}:twin",
                message=(
                    f"`jit_pair` targets for `{k.name}` must unpack as "
                    f"`(plain, plain_donated)` so call sites and the "
                    f"warmup registry can select the twin by name"
                ),
            ))

    reg = _builder_map(project)
    if reg is None or not kernels:
        return findings
    reg_mod, builders = reg
    mod_fns = {
        n.name: n for n in ast.walk(reg_mod.tree)
        if isinstance(n, ast.FunctionDef)
    }
    kernel_by_name = {k.name: k for k in kernels}
    covered: set = set()
    for key, (builder_fn, line) in sorted(builders.items()):
        if builder_fn is None:
            continue
        refs, statics = _builder_refs_and_statics(
            mod_fns, builder_fn, set(kernel_by_name)
        )
        covered |= refs
        for ref in sorted(refs):
            want = set(kernel_by_name[ref].statics)
            if statics and statics != want:
                missing = sorted(want - statics)
                extra = sorted(statics - want)
                detail = []
                if missing:
                    detail.append(f"missing {missing}")
                if extra:
                    detail.append(f"extra {extra}")
                findings.append(Finding(
                    check=CHECK, path=reg_mod.rel,
                    line=builder_fn.lineno,
                    symbol=f"{key}:statics",
                    message=(
                        f"registry builder `{builder_fn.name}` statics "
                        f"disagree with `{ref}` static_argnames "
                        f"({'; '.join(detail)}) — it would warm the "
                        f"wrong executable"
                    ),
                ))
    for name, k in sorted(kernel_by_name.items()):
        if name not in covered:
            findings.append(Finding(
                check=CHECK, path=k.module.rel, line=k.line,
                symbol=f"{name}:registry",
                message=(
                    f"kernel `{name}` has no warmup-registry builder "
                    f"(_BUILDERS) — warmed reruns will re-compile it"
                ),
            ))
    return findings
