"""``lane-safety``: attributes mutated from >= 2 lanes need a lock.

Builds the lane-annotated call graph (:mod:`analysis.callgraph`) from
the project's thread entry points — pack workers, the dedicated packer,
the H2D stager, the committer, serve worker/reader lanes, heartbeat and
fleet threads, HTTP handler threads, the implicit ``main`` dispatch
lane — then groups every ``self.attr`` / module-global mutation by its
owner and flags groups written from two or more distinct lanes where at
least one write sits outside a lock-protected ``with`` region.

Known limits (see ``docs/static-analysis.md``): writes through local
aliases and closure cells (``busy[0] += ...``) are invisible;
happens-before edges other than locks (``Thread.join``, queue handoff)
are not modeled — annotate those sites with
``# lint: ok[lane-safety] <why>`` where the safety argument is real.
"""

from __future__ import annotations

import re

from specpride_tpu.analysis.callgraph import CallGraph
from specpride_tpu.analysis.core import Finding, Project

CHECK = "lane-safety"

_LOCK_ATTR_RE = re.compile(r"(?i)(lock|cond|mutex|sem|event)")

# writes in these methods happen before the object escapes to another
# lane (construction) or after every lane joined (teardown by
# convention is NOT exempt — joins are invisible to the analysis, so
# teardown writes need the inline annotation instead)
_INIT_METHODS = frozenset({"__init__", "__new__", "__post_init__"})


def run(project: Project) -> list[Finding]:
    graph = CallGraph(project)
    # A class is "synchronized" when it owns a lock: some method takes a
    # lock-ish `with`, or some write is already lock-guarded, or an
    # attribute is lock-named.  Only synchronized classes are eligible —
    # a class with no lock at all is taken as lane-confined by design
    # (per-run/per-job instances never escape their lane), which the
    # analysis cannot distinguish from a missing lock; the docs name
    # this as the checker's main known limit.  Module globals are
    # process-shared by construction and always eligible.
    sync_classes: set[str] = set()
    for fi in graph.functions.values():
        if fi.cls and fi.uses_lock:
            sync_classes.add(f"{fi.module.name}:{fi.cls}")
        for w in fi.writes:
            if w.owner and (
                w.guarded
                or _LOCK_ATTR_RE.search(w.attr.rsplit(".", 1)[-1])
            ):
                sync_classes.add(w.owner)

    # group mutations: (owner, attr) -> [WriteSite]
    groups: dict[tuple, list] = {}
    for fi in graph.functions.values():
        for w in fi.writes:
            if _LOCK_ATTR_RE.search(w.attr.rsplit(".", 1)[-1]):
                continue  # the lock objects themselves
            if w.owner and w.owner not in sync_classes:
                continue
            groups.setdefault((w.owner, w.attr), []).append(w)

    findings: list[Finding] = []
    for (owner, attr), writes in sorted(groups.items()):
        lanes: set[str] = set()
        for w in writes:
            if w.fn.node.name in _INIT_METHODS:
                continue
            lanes.update(w.fn.lanes)
        if len(lanes) < 2:
            continue
        unguarded = [
            w for w in writes
            if not w.guarded and w.fn.node.name not in _INIT_METHODS
            # `_foo_locked` names the caller-holds-the-lock convention:
            # the lock region is real, just not lexical here
            and not w.fn.node.name.endswith("_locked")
        ]
        if not unguarded:
            continue
        target = f"{owner}.{attr}" if owner else attr
        lane_list = ", ".join(sorted(lanes))
        for w in unguarded:
            findings.append(Finding(
                check=CHECK, path=w.module.rel, line=w.line,
                symbol=target.split(":")[-1],
                message=(
                    f"`{target.split(':')[-1]}` is mutated from lanes "
                    f"[{lane_list}] but this write is outside any "
                    f"lock-protected region"
                ),
            ))
    return findings
