"""``metrics-conformance``: metric names vs the strict exposition
grammar, the docs catalog, and the pre-register-at-0 rule.

Collected from code:

* **registrations** — ``<registry>.counter/gauge/histogram("name", ...)``
  calls with a literal name (kind rules apply here);
* the **name universe** — every non-docstring string constant matching
  the project metric shape (``specpride_*``, excluding the package-name
  prefix ``specpride_tpu``), plus f-string registrations as
  prefix/suffix patterns — what the docs direction matches against.

Rules:

1. names match the Prometheus grammar and carry the project prefix;
2. counters end ``_total``; gauges/histograms do not; no name uses the
   reserved histogram suffixes ``_bucket``/``_sum``/``_count``;
3. one name, one kind (conflicting re-registration is schema drift the
   registry would reject at runtime — catch it at lint time);
4. every registered name is documented in ``docs/`` and every
   ``specpride_*`` metric token in the docs catalog resolves to a name
   (or f-string pattern) the code can actually register;
5. pre-register-at-0: counters/gauges named by the exporter's
   ``PRE_REGISTERED_FAMILIES`` contract must be zero-initialized in
   the telemetry ``__init__`` — a drain snapshot must render 0-valued
   series, not absent ones.  The flight recorder's incident families
   (``specpride_incidents_*``, one series per detector in the v6
   catalog) ride this contract: "this detector never fired" must be
   an auditable 0, not an absent series.
"""

from __future__ import annotations

import ast
import fnmatch
import re

from specpride_tpu.analysis.core import (
    Finding,
    Project,
    str_const,
    str_seq_resolved,
    walk_no_docstrings,
)

CHECK = "metrics-conformance"

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_UNIVERSE_RE = re.compile(r"specpride_[a-z0-9_]+")
_RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")
_KINDS = ("counter", "gauge", "histogram")

# every series this project exports carries the project prefix so a
# dashboard/alert namespace can never collide with another exporter's
METRIC_PREFIX = "specpride_"


class _Reg:
    def __init__(self, module, kind, name, line):
        self.module = module
        self.kind = kind
        self.name = name
        self.line = line


def _registrations(project: Project):
    regs: list[_Reg] = []
    patterns: list[tuple] = []  # (prefix, suffix) from f-strings
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KINDS
                and node.args
            ):
                continue
            name = str_const(node.args[0])
            if name is not None:
                # ALL literal registrations collected — an unprefixed
                # name is exactly the drift the prefix rule must see
                regs.append(
                    _Reg(mod, node.func.attr, name, node.lineno)
                )
                continue
            arg = node.args[0]
            if isinstance(arg, ast.JoinedStr):
                parts = arg.values
                prefix = (
                    parts[0].value
                    if parts and isinstance(parts[0], ast.Constant)
                    else ""
                )
                suffix = (
                    parts[-1].value
                    if len(parts) > 1
                    and isinstance(parts[-1], ast.Constant)
                    else ""
                )
                if str(prefix).startswith("specpride_"):
                    patterns.append((str(prefix), str(suffix)))
    return regs, patterns


def _universe(project: Project) -> set:
    names: set = set()
    for mod in project.modules:
        for node in walk_no_docstrings(mod.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                for m in _UNIVERSE_RE.finditer(node.value):
                    tok = m.group(0)
                    if not tok.startswith("specpride_tpu"):
                        names.add(tok)
    return names


def _doc_metric_tokens(project: Project):
    """``specpride_*`` metric tokens in the docs catalog, with their
    file/line.  Label suffixes (``name{kernel}``) strip; templated
    mentions (``specpride_run_<counter>_total``, brace alternation) and
    filesystem paths (``~/.cache/specpride_jax``) are skipped."""
    for rel, text in project.docs:
        for lineno, line in enumerate(text.splitlines(), 1):
            for m in re.finditer(r"specpride_[a-zA-Z0-9_<>]*", line):
                tok = m.group(0)
                if tok.startswith("specpride_tpu"):
                    continue
                if "<" in tok or ">" in tok:
                    continue  # templated family mention
                if m.start() > 0 and line[m.start() - 1] in "/.~$":
                    continue  # path or env-var tail, not a metric
                if tok.endswith("_") and line[m.end(): m.end() + 1] in (
                    "{", "*"
                ):
                    continue  # brace-alternation / glob family mention
                yield rel, lineno, tok


def _pre_register_check(project: Project) -> list[Finding]:
    hit = project.one_constant("PRE_REGISTERED_FAMILIES")
    if hit is None:
        return []
    mod, node, line = hit
    families = str_seq_resolved(node, {}) or []
    findings: list[Finding] = []
    # zero-inits live in __init__ bodies of this module's classes:
    # `<reg>.counter("name", ...).inc(0)` chains, or `self.x = r.counter
    # ("name", ...)` followed by `self.x.inc(0)` / `.set(0...)`
    zeroed: set = set()
    named_attrs: dict[str, str] = {}  # self attr -> metric name
    inits = [
        n for n in ast.walk(mod.tree)
        if isinstance(n, ast.FunctionDef) and n.name == "__init__"
    ]
    registered: dict[str, tuple] = {}  # name -> (line, kind)
    for init in inits:
        for node in ast.walk(init):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) and (
                node.func.attr in _KINDS
            ) and node.args:
                name = str_const(node.args[0])
                if name:
                    registered.setdefault(
                        name, (node.lineno, node.func.attr)
                    )
            # chained: r.counter("x", ...).inc(0) / .set(0)
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "inc", "set"
            ):
                inner = node.func.value
                zero_arg = (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value in (0, 0.0)
                )
                if not zero_arg:
                    # NB: a bare .inc() increments by 1 — that is the
                    # phantom-event miscount, not a zero-init
                    continue
                if isinstance(inner, ast.Call) and isinstance(
                    inner.func, ast.Attribute
                ) and inner.func.attr in _KINDS and inner.args:
                    name = str_const(inner.args[0])
                    if name:
                        zeroed.add(name)
                elif isinstance(inner, ast.Attribute) and isinstance(
                    inner.value, ast.Name
                ) and inner.value.id == "self":
                    name = named_attrs.get(inner.attr)
                    if name:
                        zeroed.add(name)
        for stmt in ast.walk(init):
            if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call
            ) and isinstance(stmt.value.func, ast.Attribute) and (
                stmt.value.func.attr in _KINDS
            ) and stmt.value.args:
                name = str_const(stmt.value.args[0])
                for tgt in stmt.targets:
                    if name and isinstance(
                        tgt, ast.Attribute
                    ) and isinstance(tgt.value, ast.Name) and (
                        tgt.value.id == "self"
                    ):
                        named_attrs[tgt.attr] = name
        # second pass so attr zero-inits after the binding resolve
        for node in ast.walk(init):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr in ("inc", "set"):
                zero_arg = (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value in (0, 0.0)
                )
                inner = node.func.value
                if zero_arg and isinstance(
                    inner, ast.Attribute
                ) and isinstance(inner.value, ast.Name) and (
                    inner.value.id == "self"
                ):
                    name = named_attrs.get(inner.attr)
                    if name:
                        zeroed.add(name)
    for name, (reg_line, kind) in sorted(registered.items()):
        if kind == "histogram":
            continue  # histograms appear with the first observe
        if any(
            fnmatch.fnmatchcase(name, fam) for fam in families
        ) and name not in zeroed:
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=reg_line,
                symbol=f"{name}:pre-register",
                message=(
                    f"`{name}` matches PRE_REGISTERED_FAMILIES but is "
                    f"never zero-initialized in __init__ — drain "
                    f"snapshots would omit the series instead of "
                    f"rendering 0"
                ),
            ))
    for fam in families:
        if not any(
            fnmatch.fnmatchcase(name, fam) for name in registered
        ):
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=line,
                symbol=f"{fam}:family",
                message=(
                    f"PRE_REGISTERED_FAMILIES pattern `{fam}` matches "
                    f"no registration in this module — stale contract"
                ),
            ))
    return findings


def run(project: Project) -> list[Finding]:
    regs, patterns = _registrations(project)
    if not regs:
        return []
    findings: list[Finding] = []
    kinds_by_name: dict[str, set] = {}
    for r in regs:
        kinds_by_name.setdefault(r.name, set()).add(r.kind)
        if not r.name.startswith(METRIC_PREFIX):
            findings.append(Finding(
                check=CHECK, path=r.module.rel, line=r.line,
                symbol=f"{r.name}:prefix",
                message=(
                    f"metric `{r.name}` lacks the project prefix "
                    f"`{METRIC_PREFIX}` — its series would collide "
                    f"with other exporters' namespaces"
                ),
            ))
        if not _NAME_RE.fullmatch(r.name):
            findings.append(Finding(
                check=CHECK, path=r.module.rel, line=r.line,
                symbol=f"{r.name}:grammar",
                message=(
                    f"metric name `{r.name}` violates the Prometheus "
                    f"name grammar"
                ),
            ))
        if r.kind == "counter" and not r.name.endswith("_total"):
            findings.append(Finding(
                check=CHECK, path=r.module.rel, line=r.line,
                symbol=f"{r.name}:suffix",
                message=(
                    f"counter `{r.name}` must end in `_total` "
                    f"(Prometheus counter convention)"
                ),
            ))
        if r.kind in ("gauge", "histogram") and r.name.endswith(
            "_total"
        ):
            findings.append(Finding(
                check=CHECK, path=r.module.rel, line=r.line,
                symbol=f"{r.name}:suffix",
                message=(
                    f"{r.kind} `{r.name}` must not end in `_total` — "
                    f"that suffix marks counters"
                ),
            ))
        if any(r.name.endswith(s) for s in _RESERVED_SUFFIXES):
            findings.append(Finding(
                check=CHECK, path=r.module.rel, line=r.line,
                symbol=f"{r.name}:reserved",
                message=(
                    f"metric `{r.name}` uses a reserved histogram "
                    f"suffix — scrapers will misparse the exposition"
                ),
            ))
    for name, kinds in sorted(kinds_by_name.items()):
        if len(kinds) > 1:
            first = next(r for r in regs if r.name == name)
            findings.append(Finding(
                check=CHECK, path=first.module.rel, line=first.line,
                symbol=f"{name}:kind-conflict",
                message=(
                    f"`{name}` is registered as {sorted(kinds)} in "
                    f"different places — the registry would raise at "
                    f"runtime"
                ),
            ))

    # docs coverage, both directions (only when a docs catalog exists)
    doc_tokens = list(_doc_metric_tokens(project))
    if doc_tokens:
        documented = {tok for _rel, _ln, tok in doc_tokens}

        def doc_has(name: str) -> bool:
            if name in documented:
                return True
            # histogram series are documented by their base name
            for s in _RESERVED_SUFFIXES:
                if name.endswith(s) and name[: -len(s)] in documented:
                    return True
            return False

        for r in regs:
            if not doc_has(r.name):
                findings.append(Finding(
                    check=CHECK, path=r.module.rel, line=r.line,
                    symbol=f"{r.name}:undocumented",
                    message=(
                        f"metric `{r.name}` is registered but appears "
                        f"nowhere in docs/ — add it to the catalog in "
                        f"docs/observability.md"
                    ),
                ))
        universe = _universe(project)
        for rel, lineno, tok in doc_tokens:
            base = tok
            for s in _RESERVED_SUFFIXES:
                if tok.endswith(s):
                    base = tok[: -len(s)]
            known = base in universe or any(
                base.startswith(p) and base.endswith(s)
                for p, s in patterns
            )
            if not known:
                findings.append(Finding(
                    check=CHECK, path=rel, line=lineno,
                    symbol=f"{tok}:stale-doc",
                    message=(
                        f"docs mention metric `{tok}` but no code "
                        f"registers or references that name"
                    ),
                ))
    findings += _pre_register_check(project)
    return findings
