"""``cli-flags``: the parser, the daemon-ownership tables, and the
docs must agree on every flag.

Anchors: all ``add_argument("--flag", ...)`` calls (the parser), the
``DAEMON_ONLY_FLAGS`` tuple and its ``_DAEMON_OWNED_DESTS`` mirror
(``serve/protocol.py``), and the ``docs/``/README markdown.

Rules:

1. every ``DAEMON_ONLY_FLAGS`` entry is a real parser flag — a stale
   entry silently stops protecting the daemon boot config;
2. ``DAEMON_ONLY_FLAGS`` and ``_DAEMON_OWNED_DESTS`` are exact mirrors
   under argparse dest derivation (the prefix-spelling scan and the
   parsed-namespace scan must cover the same set);
3. every long flag the parser defines appears literally (as
   ``--flag``) somewhere in ``docs/*.md`` or ``README.md``;
4. no flag is defined twice with the same spelling on one subparser
   (argparse raises at runtime — catch it at lint time).
"""

from __future__ import annotations

import ast
import re

from specpride_tpu.analysis.core import (
    Finding,
    Project,
    flag_to_dest,
    str_seq_resolved,
)

CHECK = "cli-flags"


def _parser_flags(project: Project):
    """Every literal ``--flag`` passed to an ``add_argument`` call:
    flag -> (module, first line)."""
    flags: dict[str, tuple] = {}
    per_parser: dict[tuple, list] = {}
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
            ):
                continue
            receiver = ast.unparse(node.func.value)
            for arg in node.args:
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ) and arg.value.startswith("--"):
                    flags.setdefault(arg.value, (mod, node.lineno))
                    per_parser.setdefault(
                        (mod.name, receiver, arg.value), []
                    ).append((mod, node.lineno))
    return flags, per_parser


def run(project: Project) -> list[Finding]:
    flags, per_parser = _parser_flags(project)
    if not flags:
        return []
    findings: list[Finding] = []
    for (mod_name, receiver, flag), sites in sorted(per_parser.items()):
        if len(sites) > 1:
            mod, line = sites[1]
            findings.append(Finding(
                check=CHECK, path=mod.rel, line=line,
                symbol=f"{flag}:duplicate",
                message=(
                    f"`{flag}` is added twice to parser `{receiver}` "
                    f"— argparse raises at runtime"
                ),
            ))

    daemon_hit = project.one_constant("DAEMON_ONLY_FLAGS")
    dests_hit = project.one_constant("_DAEMON_OWNED_DESTS")
    if daemon_hit is not None:
        dmod, dnode, dline = daemon_hit
        daemon_flags = str_seq_resolved(dnode, {}) or []
        for flag in daemon_flags:
            if flag not in flags:
                findings.append(Finding(
                    check=CHECK, path=dmod.rel, line=dline,
                    symbol=f"{flag}:unknown",
                    message=(
                        f"DAEMON_ONLY_FLAGS lists `{flag}` but no "
                        f"parser defines it — stale protection"
                    ),
                ))
        if dests_hit is not None:
            omod, onode, oline = dests_hit
            dests = set(str_seq_resolved(onode, {}) or [])
            want = {flag_to_dest(f) for f in daemon_flags}
            for dest in sorted(want - dests):
                findings.append(Finding(
                    check=CHECK, path=omod.rel, line=oline,
                    symbol=f"{dest}:dest-missing",
                    message=(
                        f"_DAEMON_OWNED_DESTS is missing `{dest}` "
                        f"(from DAEMON_ONLY_FLAGS) — prefix spellings "
                        f"like `--layou` would slip past the scan"
                    ),
                ))
            for dest in sorted(dests - want):
                findings.append(Finding(
                    check=CHECK, path=omod.rel, line=oline,
                    symbol=f"{dest}:dest-stale",
                    message=(
                        f"_DAEMON_OWNED_DESTS lists `{dest}` with no "
                        f"matching DAEMON_ONLY_FLAGS entry"
                    ),
                ))

    # docs coverage: every long flag documented somewhere.  Token
    # match, not substring — docs naming only `--poll-interval` must
    # not count as documenting a `--poll` flag.
    if project.docs:
        corpus = "\n".join(text for _rel, text in project.docs)
        documented = set(re.findall(r"--[a-zA-Z][\w-]*", corpus))
        for flag, (mod, line) in sorted(flags.items()):
            if flag not in documented:
                findings.append(Finding(
                    check=CHECK, path=mod.rel, line=line,
                    symbol=f"{flag}:undocumented",
                    message=(
                        f"flag `{flag}` is not documented anywhere "
                        f"under docs/ or README.md"
                    ),
                ))
    return findings
