"""Shared model for the static analyzer: project loader, findings,
inline suppressions, and the literal/docs helpers every checker uses.

The loader is CONVENTION-driven, not path-hardcoded: checkers locate
their cross-artifact anchors (``EVENT_FIELDS``, ``FAULT_SITES``,
``DAEMON_ONLY_FLAGS``, the warmup ``_BUILDERS`` table, the docs event
table) by scanning module-level assignments and ``docs/*.md`` under the
project root.  That is what lets the same checkers run over the real
tree AND over the miniature fixture packages in ``tests/test_lint.py``
— a checker whose anchors are absent reports nothing rather than
failing, so partial fixtures stay usable.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

# directories never scanned for project code (fixture trees follow the
# same conventions, so the one exclusion list serves both).  These are
# pruned ONLY at the project root: a package may legitimately own a
# `data/` or `scripts/` SUBPACKAGE (specpride_tpu/data holds the packed
# layouts), and excluding it at depth would silently blind every
# checker to it.
EXCLUDE_ROOT_DIRS = frozenset({
    "tests", "docs", "native", "notebooks", "scripts", "build", "dist",
    "data",
})

# pruned at any depth: never project code
EXCLUDE_ANY_DIRS = frozenset({
    "__pycache__", ".git", ".claude", ".pytest_cache",
})

# inline suppression: `# lint: ok[check-id] reason` (comma list allowed)
# on the finding's line.  The reason is mandatory by convention — the
# comment IS the justification the baseline file would otherwise carry.
_SUPPRESS_RE = re.compile(r"#\s*lint:\s*ok\[([a-z0-9_,\- ]+)\]")


@dataclasses.dataclass
class Finding:
    """One checker verdict, anchored for stable baseline matching.

    ``symbol`` is the durable anchor (an attribute qualname, a flag, an
    event name, ...) — fingerprints deliberately exclude the line
    number so unrelated edits above a legacy finding don't churn the
    baseline."""

    check: str
    path: str  # project-root-relative, posix separators
    line: int
    symbol: str
    message: str

    @property
    def fingerprint(self) -> tuple[str, str, str]:
        return (self.check, self.path, self.symbol)

    def to_json(self) -> dict:
        return {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
        }

    @classmethod
    def from_json(cls, rec: dict) -> "Finding":
        return cls(
            check=str(rec["check"]), path=str(rec["path"]),
            line=int(rec.get("line", 0)), symbol=str(rec["symbol"]),
            message=str(rec.get("message", "")),
        )

    def sort_key(self) -> tuple:
        return (self.check, self.path, self.line, self.symbol)


class Module:
    """One parsed project source file."""

    def __init__(self, root: str, path: str):
        self.path = path
        self.rel = os.path.relpath(path, root).replace(os.sep, "/")
        # dotted name mirrors the import system close enough for the
        # alias resolution the checkers do (packages drop __init__)
        name = self.rel[:-3].replace("/", ".")
        if name.endswith(".__init__"):
            name = name[: -len(".__init__")]
        self.name = name
        with open(path, encoding="utf-8") as fh:
            self.source = fh.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        self._suppressed: dict[int, set] | None = None

    def suppressed_at(self, line: int) -> set:
        """Check ids suppressed on ``line`` by an inline comment."""
        if self._suppressed is None:
            table: dict[int, set] = {}
            for i, text in enumerate(self.lines, 1):
                m = _SUPPRESS_RE.search(text)
                if m:
                    table[i] = {
                        tok.strip() for tok in m.group(1).split(",")
                        if tok.strip()
                    }
            self._suppressed = table
        return self._suppressed.get(line, set())


class Project:
    """The analyzed tree: parsed modules plus the docs files the
    cross-artifact checkers diff code against."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.modules: list[Module] = []
        self.errors: list[str] = []
        for path in sorted(self._iter_py(self.root)):
            try:
                self.modules.append(Module(self.root, path))
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                rel = os.path.relpath(path, self.root)
                self.errors.append(f"{rel}: unparseable ({e})")
        self._docs: list[tuple[str, str]] | None = None

    @staticmethod
    def _iter_py(root: str):
        for dirpath, dirnames, filenames in os.walk(root):
            at_root = os.path.samefile(dirpath, root)
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in EXCLUDE_ANY_DIRS
                and not d.startswith(".")
                and not (at_root and d in EXCLUDE_ROOT_DIRS)
            )
            for fn in filenames:
                if fn.endswith(".py") and not fn.startswith("__graft"):
                    yield os.path.join(dirpath, fn)

    # -- docs -----------------------------------------------------------

    @property
    def docs(self) -> list[tuple[str, str]]:
        """``(relpath, text)`` for every markdown file lint diffs
        against: ``docs/*.md`` plus the top-level ``README.md``."""
        if self._docs is None:
            out = []
            docs_dir = os.path.join(self.root, "docs")
            if os.path.isdir(docs_dir):
                for fn in sorted(os.listdir(docs_dir)):
                    if fn.endswith(".md"):
                        p = os.path.join(docs_dir, fn)
                        with open(p, encoding="utf-8") as fh:
                            out.append((f"docs/{fn}", fh.read()))
            readme = os.path.join(self.root, "README.md")
            if os.path.exists(readme):
                with open(readme, encoding="utf-8") as fh:
                    out.append(("README.md", fh.read()))
            self._docs = out
        return self._docs

    def doc(self, rel: str) -> str | None:
        for name, text in self.docs:
            if name == rel:
                return text
        return None

    # -- anchor discovery ----------------------------------------------

    def module_constants(self, name: str):
        """Every module-level ``NAME = <expr>`` assignment across the
        project, as ``(module, value_node, lineno)``."""
        for mod in self.modules:
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) and tgt.id == name:
                            yield mod, node.value, node.lineno
                elif isinstance(node, ast.AnnAssign) and node.value:
                    tgt = node.target
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        yield mod, node.value, node.lineno

    def one_constant(self, name: str):
        """The unique module-level assignment of ``name``, or None."""
        hits = list(self.module_constants(name))
        return hits[0] if len(hits) == 1 else None


# -- AST literal helpers -------------------------------------------------


def str_const(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_seq(node) -> list[str] | None:
    """String elements of a literal tuple/list/set; None if the node is
    not a purely-literal string sequence.  ``A + B`` concatenations of
    such sequences (the ``FAULT_SITES = EXECUTOR_FAULT_SITES + (...)``
    idiom) resolve when the caller passes an ``env`` of known names."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for elt in node.elts:
            s = str_const(elt)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def str_seq_resolved(node, env: dict) -> list[str] | None:
    """Like :func:`str_seq` but resolves Name references and binary
    ``+`` through ``env`` (name -> list of strings)."""
    direct = str_seq(node)
    if direct is not None:
        return direct
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = str_seq_resolved(node.left, env)
        right = str_seq_resolved(node.right, env)
        if left is not None and right is not None:
            return left + right
    if isinstance(node, ast.Call):
        # frozenset({...}) / tuple([...]) / sorted((...)) wrappers
        fn = node.func
        fname = fn.id if isinstance(fn, ast.Name) else getattr(
            fn, "attr", ""
        )
        if fname in ("frozenset", "tuple", "list", "set", "sorted") and (
            len(node.args) == 1
        ):
            return str_seq_resolved(node.args[0], env)
    return None


def dict_of_str_sets(node, env: dict | None = None) -> dict | None:
    """Parse ``{"k": frozenset({"a", ...}), ...}`` (the EVENT_FIELDS
    shape) into ``{k: set_of_strings}``; None when the node is not a
    dict literal.  Unresolvable values map to None (caller skips)."""
    if not isinstance(node, ast.Dict):
        return None
    env = env or {}
    out: dict = {}
    for k, v in zip(node.keys, node.values):
        key = str_const(k)
        if key is None:
            continue
        seq = str_seq_resolved(v, env)
        out[key] = set(seq) if seq is not None else None
    return out


def walk_no_docstrings(tree):
    """``ast.walk`` skipping docstring Constant nodes — the metrics
    universe sweep must not mistake a name quoted in prose for a
    registration."""
    doc_nodes = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef,
             ast.AsyncFunctionDef),
        ):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and isinstance(
                body[0].value, ast.Constant
            ) and isinstance(body[0].value.value, str):
                doc_nodes.add(id(body[0].value))
    for node in ast.walk(tree):
        if id(node) not in doc_nodes:
            yield node


def call_name(node: ast.Call) -> str:
    """Trailing identifier of a call target: ``f(...)`` -> ``f``,
    ``a.b.f(...)`` -> ``f``."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def kwarg(node: ast.Call, name: str):
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def has_starstar(node: ast.Call) -> bool:
    return any(kw.arg is None for kw in node.keywords)


def flag_to_dest(flag: str) -> str:
    return flag.lstrip("-").replace("-", "_")


# -- docs markdown helpers ----------------------------------------------

_CODE_SPAN_RE = re.compile(r"`([^`]+)`")


def parse_event_table(text: str) -> dict[str, dict]:
    """The docs event table: the markdown table whose header row's
    first cell is ``event``, rows ``| `name` | payload | meaning |``.

    Returns ``{event: {"required": set, "mentioned": set, "line": n}}``.
    Required fields are the backticked names in the payload cell BEFORE
    any ``plus`` marker — the documented convention for optional/
    additive fields; ``mentioned`` is every backticked name in the cell
    (the journal-schema checker holds the v4 trace-envelope fields to
    mentioned-at-least, required-or-optional)."""
    out: dict[str, dict] = {}
    in_table = False
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.lstrip().startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "event":
            in_table = True
            continue
        if not in_table or len(cells) < 2:
            continue
        if set(cells[0]) <= {"-", ":"}:  # the |---|---| separator row
            continue
        m = _CODE_SPAN_RE.fullmatch(cells[0])
        if not m:
            continue
        event = m.group(1)
        if not re.fullmatch(r"[a-z][a-z0-9_]*", event):
            continue
        payload = cells[1]
        # optional/additive fields are documented after a "(plus ...)"
        required_part = re.split(r"\(?\bplus\b", payload, maxsplit=1)[0]
        required = set(_CODE_SPAN_RE.findall(required_part))
        out[event] = {
            "required": required,
            "mentioned": set(_CODE_SPAN_RE.findall(payload)),
            "line": lineno,
        }
    return out
