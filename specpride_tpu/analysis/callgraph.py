"""Function index, best-effort call graph, and lane inference.

A **lane** is one concurrent execution context: every
``threading.Thread(target=...)`` creation site (named by its literal
``name=`` prefix when present), every ``ThreadPoolExecutor.submit``
callee, HTTP handler ``do_*`` methods, plus the implicit ``main`` lane
seeded by the functions that CREATE threads (command entry points and
lane constructors run on the dispatching thread).

Call resolution is deliberately conservative — a static lint must
under-approximate rather than hallucinate edges:

* bare names resolve through the lexical scope chain (nested siblings,
  then module level, then project imports);
* ``self.m(...)`` resolves within the enclosing class;
* other attribute calls resolve only when the method name is defined by
  exactly ONE project function AND is not a common stdlib method name
  (``get``/``put``/``join``/... would otherwise pull queue traffic into
  the graph);
* a function referenced by name in non-call position (a callback handed
  to a retry wrapper) is assumed invoked on the SAME lane — except when
  the reference is a ``Thread(target=...)`` / ``submit`` argument,
  which starts its own lane.
"""

from __future__ import annotations

import ast
import re

from specpride_tpu.analysis.core import Module, Project, kwarg

_LOCKISH_RE = re.compile(r"(?i)(lock|cond|mutex|sem)")

# attribute-call names too generic to resolve by project-wide uniqueness
_COMMON_METHODS = frozenset({
    "get", "put", "set", "add", "pop", "close", "read", "write", "open",
    "join", "start", "wait", "acquire", "release", "send", "recv",
    "items", "keys", "values", "update", "append", "extend", "clear",
    "copy", "flush", "run", "stop", "next", "submit", "result", "emit",
    "notify", "notify_all", "count", "index", "sort", "split", "strip",
    "encode", "decode", "format", "mkdir", "exists", "load", "dump",
})


class WriteSite:
    __slots__ = ("owner", "attr", "line", "guarded", "fn", "module")

    def __init__(self, owner: str, attr: str, line: int, guarded: bool,
                 fn: "FunctionInfo", module: Module):
        self.owner = owner  # class qualname for self-writes, "" = global
        self.attr = attr
        self.line = line
        self.guarded = guarded
        self.fn = fn
        self.module = module


class FunctionInfo:
    def __init__(self, module: Module, node, cls: str | None,
                 parent: "FunctionInfo | None"):
        self.module = module
        self.node = node
        self.cls = cls  # enclosing class name, if a method
        self.parent = parent  # enclosing function, if nested
        self.children: dict[str, FunctionInfo] = {}
        bits = []
        p = parent
        while p is not None:
            bits.append(p.node.name)
            p = p.parent
        prefix = ".".join(reversed(bits))
        name = node.name if not prefix else f"{prefix}.{name_of(node)}"
        if cls:
            name = f"{cls}.{name}"
        self.qualname = f"{module.name}:{name}"
        self.calls: list[tuple] = []  # resolution requests
        self.refs: list[str] = []  # names referenced in non-call position
        self.writes: list[WriteSite] = []
        self.lanes: set[str] = set()
        self.spawns: list[tuple] = []  # (target_expr, lane_name, lineno)
        self.uses_lock = False  # body contains a lock-ish `with`


def name_of(node) -> str:
    return node.name


def _is_lockish(expr_src: str) -> bool:
    return bool(_LOCKISH_RE.search(expr_src))


class _FnWalker(ast.NodeVisitor):
    """Walks ONE function body (not nested defs), collecting calls,
    name references, attribute writes with lock context, and thread
    spawns."""

    def __init__(self, fn: FunctionInfo, index: "CallGraph"):
        self.fn = fn
        self.index = index
        self.lock_depth = 0
        self.spawn_target_ids: set[int] = set()

    # -- structure ------------------------------------------------------

    def visit_FunctionDef(self, node):  # nested def: separate function
        self.index.index_function(self.fn.module, node, self.fn.cls,
                                  self.fn)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # nested class: index its methods
        self.index.index_class(self.fn.module, node)

    def visit_Lambda(self, node):
        self.generic_visit(node)

    def visit_With(self, node):
        lockish = any(
            _is_lockish(ast.unparse(item.context_expr))
            for item in node.items
        )
        for item in node.items:
            self.visit(item.context_expr)
        if lockish:
            self.fn.uses_lock = True
            self.lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self.lock_depth -= 1

    visit_AsyncWith = visit_With

    # -- writes ---------------------------------------------------------

    def _note_write(self, target) -> None:
        # unwrap subscripts: `self.d[k] = v` mutates attribute `d`
        while isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ):
            base = target.value.id
            if base == "self" and self.fn.cls:
                self.fn.writes.append(WriteSite(
                    f"{self.fn.module.name}:{self.fn.cls}", target.attr,
                    target.lineno, self.lock_depth > 0, self.fn,
                    self.fn.module,
                ))
            elif base in self.index.module_aliases.get(
                self.fn.module.name, {}
            ):
                owner = self.index.module_aliases[self.fn.module.name][
                    base
                ]
                self.fn.writes.append(WriteSite(
                    "", f"{owner}.{target.attr}", target.lineno,
                    self.lock_depth > 0, self.fn, self.fn.module,
                ))
        elif isinstance(target, ast.Name):
            if target.id in self._globals():
                self.fn.writes.append(WriteSite(
                    "", f"{self.fn.module.name}.{target.id}",
                    target.lineno, self.lock_depth > 0, self.fn,
                    self.fn.module,
                ))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._note_write(elt)

    def _globals(self) -> set:
        cached = getattr(self.fn, "_global_names", None)
        if cached is None:
            cached = set()
            for stmt in ast.walk(self.fn.node):
                if isinstance(stmt, ast.Global):
                    cached.update(stmt.names)
            self.fn._global_names = cached
        return cached

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._note_write(tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._note_write(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._note_write(node.target)
            self.visit(node.value)

    # -- calls / refs / spawns -----------------------------------------

    def _lane_name(self, call: ast.Call, target) -> str:
        name_kw = kwarg(call, "name")
        if isinstance(name_kw, ast.Constant) and isinstance(
            name_kw.value, str
        ):
            return name_kw.value
        if isinstance(name_kw, ast.JoinedStr) and name_kw.values:
            first = name_kw.values[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                return first.value.rstrip("-_ ") or "thread"
        if isinstance(target, ast.Name):
            return f"thread:{target.id}"
        if isinstance(target, ast.Attribute):
            return f"thread:{target.attr}"
        return "thread"

    def visit_Call(self, node):
        fn = node.func
        # thread spawn?
        callee = (
            fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else ""
        )
        if callee == "Thread":
            target = kwarg(node, "target")
            if target is not None:
                self.fn.spawns.append(
                    (target, self._lane_name(node, target), node.lineno)
                )
                self.spawn_target_ids.add(id(target))
        elif callee == "submit" and node.args:
            # executor.submit(fn, ...): a pool lane named for the callee
            target = node.args[0]
            lane = (
                f"pool:{target.id}" if isinstance(target, ast.Name)
                else f"pool:{target.attr}"
                if isinstance(target, ast.Attribute) else "pool"
            )
            self.fn.spawns.append((target, lane, node.lineno))
            self.spawn_target_ids.add(id(target))
        # call edge request
        if isinstance(fn, ast.Name):
            self.fn.calls.append(("name", fn.id))
        elif isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                self.fn.calls.append(("self", fn.attr))
            elif isinstance(fn.value, ast.Name):
                self.fn.calls.append(("objattr", fn.value.id, fn.attr))
            else:
                self.fn.calls.append(("attr", fn.attr))
        if isinstance(fn, ast.Attribute):
            self.visit(fn.value)
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load) and id(node) not in (
            self.spawn_target_ids
        ):
            self.fn.refs.append(node.id)

    def visit_Attribute(self, node):
        # `self._meth` referenced as a callback
        if (
            isinstance(node.ctx, ast.Load)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and id(node) not in self.spawn_target_ids
        ):
            self.fn.refs.append(f"self.{node.attr}")
        self.generic_visit(node)


class CallGraph:
    """Index + lane propagation over one :class:`Project`."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: dict[str, FunctionInfo] = {}
        self.by_module: dict[str, dict[str, FunctionInfo]] = {}
        self.methods: dict[str, list[FunctionInfo]] = {}  # name -> fns
        # per-module import aliases: local name -> project module name
        self.module_aliases: dict[str, dict[str, str]] = {}
        # per-module imported functions: local name -> qualname
        self.imported_fns: dict[str, dict[str, str]] = {}
        module_names = {m.name for m in project.modules}
        for mod in project.modules:
            self.by_module.setdefault(mod.name, {})
            self._collect_imports(mod, module_names)
        for mod in project.modules:
            for node in mod.tree.body:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self.index_function(mod, node, None, None)
                elif isinstance(node, ast.ClassDef):
                    self.index_class(mod, node)
        # resolve imported function names now every def is indexed
        for mod_name, imports in self.imported_fns.items():
            for local, qual in list(imports.items()):
                if qual not in self.functions:
                    del imports[local]
        self._walk_bodies()
        self.lanes = self._propagate()

    # -- indexing -------------------------------------------------------

    def _collect_imports(self, mod: Module, module_names: set) -> None:
        aliases: dict[str, str] = {}
        fns: dict[str, str] = {}
        pkg_prefixes = {n.split(".")[0] for n in module_names}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in module_names:
                        aliases[a.asname or a.name.split(".")[0]] = (
                            a.name
                        )
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                if base.split(".")[0] not in pkg_prefixes:
                    continue
                for a in node.names:
                    full = f"{base}.{a.name}"
                    local = a.asname or a.name
                    if full in module_names:
                        aliases[local] = full
                    elif base in module_names:
                        fns[local] = f"{base}:{a.name}"
        self.module_aliases[mod.name] = aliases
        self.imported_fns[mod.name] = fns

    def index_class(self, mod: Module, node: ast.ClassDef) -> None:
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.index_function(mod, item, node.name, None)
        # HTTP handler classes: do_* methods run on server threads
        bases = [ast.unparse(b) for b in node.bases]
        if any(b.endswith(("RequestHandler", "Handler")) for b in bases):
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and (
                    item.name.startswith("do_") or item.name == "handle"
                ):
                    fi = self.functions.get(
                        f"{mod.name}:{node.name}.{item.name}"
                    )
                    if fi is not None:
                        fi.lanes.add("http-handler")

    def index_function(self, mod: Module, node, cls: str | None,
                       parent: FunctionInfo | None) -> FunctionInfo:
        fi = FunctionInfo(mod, node, cls, parent)
        self.functions[fi.qualname] = fi
        if parent is not None:
            parent.children[node.name] = fi
        else:
            self.by_module[mod.name][
                node.name if not cls else f"{cls}.{node.name}"
            ] = fi
        self.methods.setdefault(node.name, []).append(fi)
        return fi

    def _walk_bodies(self) -> None:
        # worklist, not a snapshot: walking a body INDEXES its nested
        # defs (visit_FunctionDef), and those must be walked too — a
        # snapshot loop would leave every nested thread body (the
        # repo's dominant concurrency pattern: _packer/_stager/_worker
        # closures) with empty call/write info and kill propagation
        walked: set[str] = set()
        while True:
            pending = [
                fi for q, fi in list(self.functions.items())
                if q not in walked
            ]
            if not pending:
                break
            for fi in pending:
                walked.add(fi.qualname)
                walker = _FnWalker(fi, self)
                for stmt in fi.node.body:
                    walker.visit(stmt)

    # -- resolution -----------------------------------------------------

    def resolve_name(self, caller: FunctionInfo, name: str):
        p = caller
        while p is not None:
            if name in p.children:
                return p.children[name]
            p = p.parent
        mod_fns = self.by_module.get(caller.module.name, {})
        if name in mod_fns:
            return mod_fns[name]
        if caller.cls and f"{caller.cls}.{name}" in mod_fns:
            return mod_fns[f"{caller.cls}.{name}"]
        qual = self.imported_fns.get(caller.module.name, {}).get(name)
        if qual:
            return self.functions.get(qual)
        return None

    def resolve_call(self, caller: FunctionInfo, call: tuple):
        kind = call[0]
        if kind == "name":
            return self.resolve_name(caller, call[1])
        if kind == "self":
            if caller.cls:
                qual = f"{caller.module.name}:{caller.cls}.{call[1]}"
                if qual in self.functions:
                    return self.functions[qual]
            return self._unique_method(call[1])
        if kind == "objattr":
            base, meth = call[1], call[2]
            owner = self.module_aliases.get(caller.module.name, {}).get(
                base
            )
            if owner:
                return self.by_module.get(owner, {}).get(meth)
            return self._unique_method(meth)
        if kind == "attr":
            return self._unique_method(call[1])
        return None

    def _unique_method(self, name: str):
        if name in _COMMON_METHODS:
            return None
        hits = self.methods.get(name, [])
        return hits[0] if len(hits) == 1 else None

    def resolve_spawn_target(self, caller: FunctionInfo, target):
        if isinstance(target, ast.Name):
            return self.resolve_name(caller, target.id)
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ):
            if target.value.id == "self" and caller.cls:
                qual = (
                    f"{caller.module.name}:{caller.cls}.{target.attr}"
                )
                return self.functions.get(qual)
            return self._unique_method(target.attr)
        return None

    # -- lane propagation ----------------------------------------------

    def _propagate(self) -> dict[str, set]:
        """Assign lanes to functions.  Returns lane -> entry qualnames."""
        entries: dict[str, set] = {}

        def seed(fi: FunctionInfo, lane: str) -> None:
            entries.setdefault(lane, set()).add(fi.qualname)

        for fi in self.functions.values():
            for target, lane, _line in fi.spawns:
                tgt = self.resolve_spawn_target(fi, target)
                if tgt is not None:
                    seed(tgt, lane)
            if fi.spawns or (
                fi.parent is None and not fi.cls
                and (fi.node.name.startswith("cmd_")
                     or fi.node.name == "main")
            ):
                seed(fi, "main")
        for fi in self.functions.values():
            for lane in fi.lanes:  # pre-seeded (http handlers)
                entries.setdefault(lane, set()).add(fi.qualname)

        for lane, quals in entries.items():
            visited: set[str] = set()
            stack = [self.functions[q] for q in quals]
            while stack:
                fi = stack.pop()
                if fi.qualname in visited:
                    continue
                visited.add(fi.qualname)
                fi.lanes.add(lane)
                spawn_ids = set()
                for target, _lane, _line in fi.spawns:
                    tgt = self.resolve_spawn_target(fi, target)
                    if tgt is not None:
                        spawn_ids.add(tgt.qualname)
                nexts = []
                for call in fi.calls:
                    tgt = self.resolve_call(fi, call)
                    if tgt is not None:
                        nexts.append(tgt)
                for ref in fi.refs:
                    if ref.startswith("self."):
                        tgt = self.resolve_call(fi, ("self", ref[5:]))
                    else:
                        tgt = self.resolve_name(fi, ref)
                    if tgt is not None and tgt.qualname not in spawn_ids:
                        nexts.append(tgt)
                for tgt in nexts:
                    if tgt.qualname not in visited:
                        stack.append(tgt)
        return entries
