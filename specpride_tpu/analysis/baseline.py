"""Baseline / suppression semantics for ``specpride lint``.

The committed baseline (``lint-baseline.json`` at the project root)
holds legacy findings that must not block CI, each with a mandatory
``reason`` — an entry without one is itself a finding.  Matching is by
fingerprint ``(check, path, symbol)``; line numbers are deliberately
excluded so edits above a legacy site don't churn the file.

Stale entries (no longer matching any finding) are reported so the
file shrinks as debt is paid; they don't fail the run on their own —
``--update-baseline`` rewrites the file from the current findings.
"""

from __future__ import annotations

import json
import os

from specpride_tpu.analysis.core import Finding

BASELINE_NAME = "lint-baseline.json"
BASELINE_VERSION = 1


class Baseline:
    def __init__(self, entries: list[dict], path: str | None = None):
        self.path = path
        self.entries = entries
        self._index: dict[tuple, dict] = {}
        for e in entries:
            key = (
                str(e.get("check", "")), str(e.get("path", "")),
                str(e.get("symbol", "")),
            )
            self._index[key] = e

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([], path=path)
        with open(path, encoding="utf-8") as fh:
            payload = json.load(fh)
        entries = payload.get("suppressions", [])
        if not isinstance(entries, list):
            raise ValueError(f"{path}: 'suppressions' must be a list")
        return cls(entries, path=path)

    def match(self, finding: Finding) -> dict | None:
        return self._index.get(finding.fingerprint)

    def split(self, findings: list[Finding],
              select: list[str] | None = None):
        """``(new, baselined, stale_entries, bad_entries)``.

        With ``select``, staleness and missing-reason checks cover only
        the selected checkers' entries — a one-checker run produces no
        findings for the others, and reporting their still-valid
        suppressions as 'stale, remove it' would talk a maintainer
        into deleting live debt records."""
        new: list[Finding] = []
        baselined: list[Finding] = []
        hit: set = set()
        for f in findings:
            entry = self.match(f)
            if entry is None:
                new.append(f)
            else:
                baselined.append(f)
                hit.add(f.fingerprint)

        def selected(e: dict) -> bool:
            return not select or str(e.get("check", "")) in select

        stale = [
            e for key, e in sorted(self._index.items())
            if key not in hit and selected(e)
        ]
        bad = [
            e for e in self.entries
            if not str(e.get("reason", "")).strip() and selected(e)
        ]
        return new, baselined, stale, bad

    @staticmethod
    def write(
        path: str, findings: list[Finding],
        existing: "Baseline | None" = None,
        select: list[str] | None = None,
    ) -> None:
        """Rewrite the baseline from current findings.

        New entries get an empty reason the committer must fill — CI
        treats a reason-less entry as a finding, so a thoughtless
        update cannot silently grandfather new debt.  ``existing``
        reasons carry forward on matching fingerprints, and with
        ``select`` the rewrite touches ONLY the selected checkers'
        entries — a one-checker refresh must not delete five other
        checkers' justified debt."""
        entries = []
        seen: set = set()
        if existing is not None and select:
            for e in existing.entries:
                if str(e.get("check", "")) not in select:
                    entries.append(e)
        old = existing._index if existing is not None else {}
        for f in sorted(findings, key=Finding.sort_key):
            if f.fingerprint in seen:
                continue
            seen.add(f.fingerprint)
            prior = old.get(f.fingerprint, {})
            entries.append({
                "check": f.check,
                "path": f.path,
                "symbol": f.symbol,
                "reason": str(prior.get("reason", "")),
                "message": f.message,
            })
        payload = {"version": BASELINE_VERSION, "suppressions": entries}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
