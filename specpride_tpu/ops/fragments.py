"""Peptide fragment theory: monoisotopic masses, b/y ion m/z generation and
tolerance-window peak annotation.

The reference consumes this capability from spectrum_utils
(``annotate_peptide_fragments`` at ref src/benchmark.py:47-52 and
ref src/plot_cluster.py:33-41) and pyteomics (``parser.fast_valid`` at
ref src/benchmark.py:41, ``mass.nist_mass`` at
ref src/average_spectrum_clustering.py:6).  Neither library is a dependency
here; the tables below are the standard IUPAC/Unimod monoisotopic values.

The annotation match itself (peak within a ppm/Da window of any theoretical
fragment) is a host-side vectorised searchsorted (``match_fragments``);
``fraction_of_by_batch`` amortises it across many representatives (one
fragment-table build per unique peptide/charge, one window match per
group) so evaluation stays sublinear in Python overhead.  No device kernel
exists for this: fragment tables are tiny (tens of entries) and the match
is memory-bound — shipping peaks over the host link would cost more than
the match itself (same economics as ``native/cosine.cpp``).
"""

from __future__ import annotations

import numpy as np

# Monoisotopic masses (Da).  PROTON_MASS is the H+ mass used for
# neutral-mass arithmetic (ref src/average_spectrum_clustering.py:6:
# pyteomics mass.nist_mass['H+'][0][0]).
PROTON_MASS = 1.00727646677
H_MASS = 1.0078250319
O_MASS = 15.9949146221
WATER_MASS = 2 * H_MASS + O_MASS  # 18.0105646...

# Standard amino-acid residue monoisotopic masses.
RESIDUE_MASSES: dict[str, float] = {
    "G": 57.02146, "A": 71.03711, "S": 87.03203, "P": 97.05276,
    "V": 99.06841, "T": 101.04768, "C": 103.00919, "L": 113.08406,
    "I": 113.08406, "N": 114.04293, "D": 115.02694, "Q": 128.05858,
    "K": 128.09496, "E": 129.04259, "M": 131.04049, "H": 137.05891,
    "F": 147.06841, "R": 156.10111, "Y": 163.06333, "W": 186.07931,
    "U": 150.95364, "O": 237.14773,
}

# Common fixed/variable modification deltas for MaxQuant-style annotations.
MOD_MASSES: dict[str, float] = {
    "ox": 15.9949146221,          # oxidation (M)
    "oxidation": 15.9949146221,
    "ac": 42.0105646863,          # acetyl
    "acetyl": 42.0105646863,
    "ph": 79.96633,               # phospho
    "phospho": 79.96633,
    "cam": 57.02146,              # carbamidomethyl
    "carbamidomethyl": 57.02146,
}

def is_valid_peptide(sequence: str) -> bool:
    """Capability of pyteomics ``parser.fast_valid``
    (ref src/benchmark.py:41): every character is a standard residue."""
    return bool(sequence) and all(c in RESIDUE_MASSES for c in sequence)


def _scan_mod(sequence: str, start: int) -> tuple[str, int]:
    """Read a parenthesised modification starting at ``start`` (which must be
    '('), handling MaxQuant's nested form '(Oxidation (M))'.  Returns the
    inner name and the index one past the closing paren."""
    depth = 0
    for i in range(start, len(sequence)):
        if sequence[i] == "(":
            depth += 1
        elif sequence[i] == ")":
            depth -= 1
            if depth == 0:
                return sequence[start + 1 : i], i + 1
    raise ValueError(f"unbalanced modification in {sequence!r}")


def parse_peptide(sequence: str) -> tuple[list[str], list[float]]:
    """Parse a peptide with optional '(mod)' annotations into residues and
    per-residue mass deltas.

    Accepts MaxQuant 'Modified sequence' dialect: flanking underscores,
    nested-paren mod names ('(Oxidation (M))'), and N-terminal mods before
    the first residue ('(ac)PEPTIDEK' — the delta attaches to the first
    residue, as N-term mods ride the b1 ion).  Unknown modifications raise
    ValueError.
    """
    residues: list[str] = []
    deltas: list[float] = []
    nterm_delta = 0.0
    i = 0
    while i < len(sequence):
        c = sequence[i]
        if c == "(":
            name, i = _scan_mod(sequence, i)
            key = name.strip().lower().split(" ")[0].split("(")[0].strip()
            if key not in MOD_MASSES:
                raise ValueError(f"unknown modification {name!r} in {sequence!r}")
            if residues:
                deltas[-1] += MOD_MASSES[key]
            else:
                nterm_delta += MOD_MASSES[key]
            continue
        if c == "_":  # MaxQuant flanking underscores
            i += 1
            continue
        if c not in RESIDUE_MASSES:
            raise ValueError(f"unknown residue {c!r} in {sequence!r}")
        residues.append(c)
        deltas.append(0.0)
        i += 1
    if nterm_delta:
        if not residues:
            raise ValueError(f"modification with no residues in {sequence!r}")
        deltas[0] += nterm_delta
    return residues, deltas


def peptide_mass(sequence: str) -> float:
    """Neutral monoisotopic peptide mass (residues + water)."""
    residues, deltas = parse_peptide(sequence)
    return sum(RESIDUE_MASSES[r] for r in residues) + sum(deltas) + WATER_MASS


def fragment_mzs(
    sequence: str,
    ion_types: str = "by",
    max_charge: int = 1,
) -> np.ndarray:
    """All theoretical fragment m/z values for the given ion types/charges.

    b_k = prefix residue mass + z*proton, y_k = suffix residue mass + water
    + z*proton; a_k = b_k - CO.  Fragment lengths 1..len-1, charges
    1..max_charge.  This is the capability of spectrum_utils'
    ``_get_theoretical_peptide_fragments`` (ref src/plot_cluster.py:36-38).
    """
    residues, deltas = parse_peptide(sequence)
    masses = np.array([RESIDUE_MASSES[r] + d for r, d in zip(residues, deltas)])
    if masses.size < 2:
        return np.array([])
    prefix = np.cumsum(masses)[:-1]  # b_1 .. b_{n-1}
    suffix = np.cumsum(masses[::-1])[:-1]  # y_1 .. y_{n-1}
    co_mass = 12.0 + O_MASS

    neutral: list[np.ndarray] = []
    for ion in ion_types:
        if ion == "b":
            neutral.append(prefix)
        elif ion == "y":
            neutral.append(suffix + WATER_MASS)
        elif ion == "a":
            neutral.append(prefix - co_mass)
        else:
            raise ValueError(f"unsupported ion type {ion!r}")
    frags = np.concatenate(neutral)

    mzs = []
    for z in range(1, max_charge + 1):
        mzs.append((frags + z * PROTON_MASS) / z)
    return np.sort(np.concatenate(mzs))


def fragment_annotations(
    sequence: str,
    ion_types: str = "by",
    max_charge: int = 1,
) -> tuple[np.ndarray, list[str]]:
    """``fragment_mzs`` with ion labels: (sorted m/z, aligned labels like
    ``b3`` / ``y5^2+``) — the identity information spectrum_utils renders
    on its annotated mirror plots (ref src/plot_cluster.py:33-45), which
    ``viz.mirror_plot`` writes next to matched peaks."""
    residues, deltas = parse_peptide(sequence)
    masses = np.array(
        [RESIDUE_MASSES[r] + d for r, d in zip(residues, deltas)]
    )
    if masses.size < 2:
        return np.array([]), []
    prefix = np.cumsum(masses)[:-1]
    suffix = np.cumsum(masses[::-1])[:-1]
    co_mass = 12.0 + O_MASS

    neutral: list[np.ndarray] = []
    labels: list[str] = []
    ks = [str(k) for k in range(1, masses.size)]
    for ion in ion_types:
        if ion == "b":
            neutral.append(prefix)
        elif ion == "y":
            neutral.append(suffix + WATER_MASS)
        elif ion == "a":
            neutral.append(prefix - co_mass)
        else:
            raise ValueError(f"unsupported ion type {ion!r}")
        labels.extend(ion + k for k in ks)
    frags = np.concatenate(neutral)

    mzs, labs = [], []
    for z in range(1, max_charge + 1):
        mzs.append((frags + z * PROTON_MASS) / z)
        suffix_z = "" if z == 1 else f"^{z}+"
        labs.extend(lab + suffix_z for lab in labels)
    flat = np.concatenate(mzs)
    order = np.argsort(flat, kind="stable")
    return flat[order], [labs[i] for i in order]


def match_fragments(
    mz: np.ndarray,
    fragment_mz: np.ndarray,
    tol: float = 50.0,
    tol_mode: str = "ppm",
) -> np.ndarray:
    """Boolean mask: which peaks fall within the tolerance window of any
    theoretical fragment (the annotation capability of ref
    src/benchmark.py:47-52, 50 ppm)."""
    if fragment_mz.size == 0 or mz.size == 0:
        return np.zeros(mz.shape, dtype=bool)
    frag = np.sort(fragment_mz)
    idx = np.searchsorted(frag, mz)
    lo = frag[np.clip(idx - 1, 0, frag.size - 1)]
    hi = frag[np.clip(idx, 0, frag.size - 1)]
    nearest = np.minimum(np.abs(mz - lo), np.abs(mz - hi))
    if tol_mode == "ppm":
        window = mz * tol * 1e-6
    else:
        window = np.full_like(mz, tol)
    return nearest <= window


def _by_fragment_table(sequence: str, max_charge: int) -> np.ndarray | None:
    """Sorted b/y fragment m/z table, or None for unparseable / too-short
    sequences (which score 0, ref src/benchmark.py:41-43)."""
    try:
        residues, _ = parse_peptide(sequence)
    except ValueError:
        return None
    if not residues or len(residues) < 2:
        return None
    return fragment_mzs(sequence, "by", max_charge)


def fraction_of_by(
    sequence: str,
    precursor_mz: float,
    precursor_charge: int,
    mz: np.ndarray,
    intensity: np.ndarray,
    tol: float = 50.0,
    tol_mode: str = "ppm",
    min_mz: float = 100.0,
    max_mz: float = 1400.0,
) -> float:
    """Fraction of total ion current explained by b/y fragments.

    Reimplements ref src/benchmark.py:40-61 (whose body references an
    undefined ``spectrum`` variable — a known reference bug; this is the
    working version).  Preprocessing per ref :49-50: restrict to
    [min_mz, max_mz], remove peaks within the tolerance window of the
    precursor.  Invalid sequences score 0 (ref :41-43).
    """
    max_charge = max(1, precursor_charge - 1)
    frags = _by_fragment_table(sequence, max_charge)
    if frags is None:
        return 0.0
    return _fraction_with_table(
        frags, precursor_mz, mz, intensity, tol, tol_mode, min_mz, max_mz
    )


def _fraction_with_table(
    frags: np.ndarray,
    precursor_mz: float,
    mz: np.ndarray,
    intensity: np.ndarray,
    tol: float,
    tol_mode: str,
    min_mz: float,
    max_mz: float,
) -> float:
    mz = np.asarray(mz, dtype=np.float64)
    intensity = np.asarray(intensity, dtype=np.float64)

    keep = (mz >= min_mz) & (mz <= max_mz)
    if tol_mode == "ppm":
        prec_window = precursor_mz * tol * 1e-6
    else:
        prec_window = tol
    keep &= np.abs(mz - precursor_mz) > prec_window
    mz, intensity = mz[keep], intensity[keep]
    if mz.size == 0:
        return 0.0

    matched = match_fragments(mz, frags, tol, tol_mode)
    total = float(intensity.sum())
    if total <= 0.0:
        return 0.0
    return float(intensity[matched].sum()) / total


def fraction_of_by_batch(
    sequences: "list[str | None]",
    precursor_mz: np.ndarray,
    precursor_charge: np.ndarray,
    spectra_mz: "list[np.ndarray]",
    spectra_intensity: "list[np.ndarray]",
    tol: float = 50.0,
    tol_mode: str = "ppm",
    min_mz: float = 100.0,
    max_mz: float = 1400.0,
) -> np.ndarray:
    """``fraction_of_by`` over many representatives with the expensive
    per-call work amortised: ONE peptide parse + fragment-table build per
    unique (sequence, charge) pair — real runs identify the same peptide
    across many clusters — and the per-spectrum window match unchanged
    (so each entry equals its ``fraction_of_by`` value bit for bit).
    ``None`` sequences yield NaN (caller decides how to report "no
    peptide"); unparseable sequences yield 0.0 as in the scalar form."""
    n = len(sequences)
    out = np.full(n, np.nan, dtype=np.float64)
    tables: dict[tuple[str, int], np.ndarray | None] = {}
    for i, seq in enumerate(sequences):
        if seq is None:
            continue
        max_charge = max(1, int(precursor_charge[i]) - 1)
        key = (seq, max_charge)
        if key not in tables:
            tables[key] = _by_fragment_table(seq, max_charge)
        frags = tables[key]
        if frags is None:
            out[i] = 0.0
            continue
        out[i] = _fraction_with_table(
            frags, float(precursor_mz[i]), spectra_mz[i],
            spectra_intensity[i], tol, tol_mode, min_mz, max_mz,
        )
    return out
