"""K2: similarity device kernels — medoid selection and binned cosine.

Medoid (most-similar representative).  TPU-native replacement for the O(n²)
Python loop that crosses into OpenMS C++ per pair at ref
src/most_similar_representative.py:91-93: all members of a cluster are binned
once into a dense 0/1 occupancy matrix ``O`` (member × grid), and the shared
occupied-bin counts for EVERY pair come from one batched gram matmul
``S = O @ O.T`` on the MXU.  xcorr prescore = S / min(raw peak counts)
(the pyOpenMS ``XQuestScores::xCorrelationPrescore`` capability, ref :15),
distance = 1 − prescore, and the reference's total-distance semantics —
upper-triangular fill including the diagonal, summed row + column, so the
self-distance counts twice (ref :88-100) — become row-sum + diagonal.
Tie-break: lowest index wins (ref :103-110) = ``jnp.argmin`` first-minimum.

Binned cosine (quality metric, ref src/benchmark.py:11-38).  The reference
grid is ~0.005 Da over [−space/2, max m/z of the pair) — ~400k bins, far too
wasteful to materialise per pair.  Instead each (representative, member) pair
is scored with a sort/segment kernel: concatenate the two spectra's
(precomputed f64) bin ids as a two-channel value array, one stable sort
groups equal bins, segmented sums give per-bin intensity totals for each
channel, and dot/norms are plain reductions — O(P log P) per pair with no
dense grid.  ``sum(segA * segB)`` is exactly ``vecA @ vecB`` of the dense
grid vectors because bins occupied by only one channel contribute zero.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import CosineConfig, MedoidConfig


# ---------------------------------------------------------------------------
# Medoid
# ---------------------------------------------------------------------------

def _occupancy(bins: jax.Array, grid: int) -> jax.Array:
    """(M, P) int32 bins (sentinel = grid) → (M, grid) 0/1 float32."""
    def one(b):
        counts = jnp.zeros((grid,), jnp.float32).at[b].add(1.0, mode="drop")
        return jnp.minimum(counts, 1.0)

    return jax.vmap(one)(bins)


@functools.partial(jax.jit, static_argnames=("grid",))
def shared_bins_batch(bins: jax.Array, grid: int) -> jax.Array:
    """(B, M, P) i32 bins (sentinel = grid) → (B, M, M) i32 shared
    occupied-bin counts for every member pair, via one batched gram matmul.

    The counts are exact small integers; the final prescore division,
    total-distance sum and lowest-index argmin (ref
    src/most_similar_representative.py:95-110) happen host-side in float64
    (``backends.tpu_backend.TpuBackend.medoid_indices``) — per-pair f32
    division on device rounds differently from the reference's f64 and can
    flip exact-tie medoid picks.  Device does the O(M²·G) work, host the
    O(M²) finalize.
    """
    def one(b):
        occ = _occupancy(b, grid)
        return (occ @ occ.T).astype(jnp.int32)  # MXU

    return jax.vmap(one)(bins)


def medoid_finalize(
    shared: "np.ndarray",  # (B, M, M) int
    n_peaks: "np.ndarray",  # (B, M) int raw peak counts
    member_mask: "np.ndarray",  # (B, M) bool
    n_members: "np.ndarray",  # (B,) int
) -> "np.ndarray":
    """Host-side float64 finalize: prescore = shared / min(raw counts),
    distance = 1 − prescore, total = row sum + diagonal (the triangular
    fill's double-counted self-distance, ref
    src/most_similar_representative.py:88-100), lowest-index argmin."""
    import numpy as np

    n = n_peaks.astype(np.float64)
    min_n = np.minimum(n[:, :, None], n[:, None, :])
    with np.errstate(invalid="ignore", divide="ignore"):
        prescore = np.where(
            min_n > 0, shared.astype(np.float64) / np.maximum(min_n, 1.0), 0.0
        )
    dist = 1.0 - prescore
    pair_ok = member_mask[:, :, None] & member_mask[:, None, :]
    dist = np.where(pair_ok, dist, 0.0)
    diag = np.einsum("bii->bi", dist)
    total = (dist.sum(axis=2) + diag) / np.maximum(
        n_members.astype(np.float64)[:, None], 1.0
    )
    total = np.where(member_mask, total, np.inf)
    return np.argmin(total, axis=1).astype(np.int32)


# ---------------------------------------------------------------------------
# Binned cosine
# ---------------------------------------------------------------------------

def _pair_cosine(
    bins_a: jax.Array,  # (Pa,) i32, sentinel = huge
    int_a: jax.Array,  # (Pa,) f32, 0 where invalid
    bins_b: jax.Array,  # (Pb,) i32
    int_b: jax.Array,  # (Pb,) f32
    n_edges: jax.Array,  # () i32: pair edge count (max of the two spectra)
):
    # peaks beyond the pair's last grid edge are excluded
    # (ref src/benchmark.py:20-22); bins are f64-exact from the host
    sent = jnp.int32(2**30)
    last_bin = n_edges - 2  # edges-1 bins; exact-equality edge case measure-zero
    ba = jnp.where(bins_a <= last_bin, bins_a, sent)
    bb = jnp.where(bins_b <= last_bin, bins_b, sent)

    keys = jnp.concatenate([ba, bb])
    va = jnp.concatenate([jnp.where(ba < sent, int_a, 0.0), jnp.zeros_like(int_b)])
    vb = jnp.concatenate([jnp.zeros_like(int_a), jnp.where(bb < sent, int_b, 0.0)])

    order = jnp.argsort(keys, stable=True)
    k = keys[order]
    sa = va[order]
    sb = vb[order]

    total = keys.shape[0]
    new_seg = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (k[1:] != k[:-1]).astype(jnp.int32)]
    )
    seg = jnp.cumsum(new_seg)
    seg_a = jax.ops.segment_sum(sa, seg, num_segments=total, indices_are_sorted=True)
    seg_b = jax.ops.segment_sum(sb, seg, num_segments=total, indices_are_sorted=True)

    dot = jnp.sum(seg_a * seg_b)
    na = jnp.sum(seg_a * seg_a)
    nb = jnp.sum(seg_b * seg_b)
    ok = (na > 0) & (nb > 0)
    return jnp.where(ok, dot / jnp.sqrt(jnp.maximum(na * nb, 1e-30)), 0.0)


@jax.jit
def cosine_rep_vs_members(
    rep_bins: jax.Array,  # (B, Pr) i32
    rep_int: jax.Array,  # (B, Pr) f32
    rep_edges: jax.Array,  # (B,) i32
    mem_bins: jax.Array,  # (B, M, P) i32
    mem_int: jax.Array,  # (B, M, P) f32
    mem_edges: jax.Array,  # (B, M) i32
    member_mask: jax.Array,  # (B, M) bool
    n_members: jax.Array,  # (B,) i32
):
    """Average binned cosine of each cluster's representative to its members
    (ref src/benchmark.py:31-38).  Returns ((B,) mean cosine, (B, M) pair
    cosines)."""

    def per_cluster(rb, ri, re, mb, mi, me, mask, n):
        pair = jax.vmap(
            lambda b, i, e: _pair_cosine(rb, ri, b, i, jnp.maximum(re, e))
        )(mb, mi, me)
        pair = jnp.where(mask, pair, 0.0)
        mean = jnp.sum(pair) / jnp.maximum(n.astype(jnp.float32), 1.0)
        return mean, pair

    return jax.vmap(per_cluster)(
        rep_bins, rep_int, rep_edges, mem_bins, mem_int, mem_edges,
        member_mask, n_members,
    )
