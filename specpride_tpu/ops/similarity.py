"""K2: similarity device kernels — medoid selection and binned cosine.

Medoid (most-similar representative).  TPU-native replacement for the O(n²)
Python loop that crosses into OpenMS C++ per pair at ref
src/most_similar_representative.py:91-93: all members of a cluster are binned
once into a dense 0/1 occupancy matrix ``O`` (member × grid), and the shared
occupied-bin counts for EVERY pair come from one batched gram matmul
``S = O @ O.T`` on the MXU.  xcorr prescore = S / min(raw peak counts)
(the pyOpenMS ``XQuestScores::xCorrelationPrescore`` capability, ref :15),
distance = 1 − prescore, and the reference's total-distance semantics —
upper-triangular fill including the diagonal, summed row + column, so the
self-distance counts twice (ref :88-100) — become row-sum + diagonal.
Tie-break: lowest index wins (ref :103-110) = ``jnp.argmin`` first-minimum.

Binned cosine (quality metric, ref src/benchmark.py:11-38).  The reference
grid is ~0.005 Da over [−space/2, max m/z of the pair) — ~400k bins, far too
wasteful to materialise per pair.  Instead each (representative, member) pair
is scored with a sort/segment kernel: concatenate the two spectra's
(precomputed f64) bin ids as a two-channel value array, one stable sort
groups equal bins, segmented sums give per-bin intensity totals for each
channel, and dot/norms are plain reductions — O(P log P) per pair with no
dense grid.  ``sum(segA * segB)`` is exactly ``vecA @ vecB`` of the dense
grid vectors because bins occupied by only one channel contribute zero.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from specpride_tpu.config import CosineConfig, MedoidConfig
from specpride_tpu.ops.jit_util import jit_pair


# ---------------------------------------------------------------------------
# Medoid
# ---------------------------------------------------------------------------

_SENT = jnp.int32(2**30)  # padding sentinel for global bin ids


def _shared_bins_packed(
    bins: jax.Array,  # (B, K) i32 GLOBAL bins, PRE-SORTED (bin, member)
    member_id: jax.Array,  # (B, K) i32 in [0, m], same order, padding = m
    m: int,
    # pow2 >= longest same-(row, bin) element run; None = K (always safe —
    # a run can never exceed the row width).  A too-small lcap would
    # silently drop occupancy bits, so there is no small default.
    lcap: int | None = None,
) -> jax.Array:
    """(B, M, M) shared occupied-bin counts for every member pair.

    Scatter-free bitmask formulation (every scatter flavor — add OR set —
    serialized on TPU and dominated this kernel at ~600 ms/0.5M rows):
    rows arrive PRE-SORTED by (bin, member) from the host, each bin run's
    member-presence set accumulates as int32 BITMASKS via a segmented
    OR-scan over the flattened batch (``ops.segments.seg_scan_or``,
    ceil(m/32) lanes), masks are read at run ends, unpacked to a 0/1
    occupancy tensor by shifts, and all pairwise counts come from one
    batched gram einsum on the MXU.  Bin ids are global grid positions
    (``floor(mz / bin_size)`` in f64 on the host) — pairwise intersections
    don't care about a per-cluster origin.  Counts return as uint16: D2H
    bytes are the bottleneck on tunneled hosts, and counts are bounded by
    per-member peak counts (the driver asserts < 2**16)."""
    from specpride_tpu.ops import segments as sg

    # reduced-precision packed inputs (--precision): int16-narrowed bin /
    # member channels upcast at entry — exact (pure integer narrowing),
    # and the in-kernel composites/shifts stay int32 math
    bins = bins.astype(jnp.int32)
    member_id = member_id.astype(jnp.int32)

    b, k = bins.shape
    if lcap is None:
        lcap = k
    n = b * k
    fb = bins.reshape(n)
    fm = member_id.reshape(n)
    ok = (fm < m) & (fb < _SENT)

    # run starts: new (row, bin) pair — row boundaries every k elements
    row_start = (jnp.arange(n, dtype=jnp.int32) % k) == 0
    starts = sg.run_starts(fb) | row_start
    first_of_mb = starts | jnp.concatenate(
        [jnp.ones((1,), bool), fm[1:] != fm[:-1]]
    )
    contrib = ok & first_of_mb
    mm = jnp.clip(fm, 0, m - 1)

    lanes = []
    for lane in range((m + 31) // 32):
        in_lane = contrib & (mm >= lane * 32) & (mm < (lane + 1) * 32)
        lanes.append(
            jnp.where(
                in_lane, jnp.int32(1) << (mm - lane * 32), jnp.int32(0)
            )
        )
    masks = sg.seg_scan_or(starts, tuple(lanes), lcap)

    is_end = sg.run_ends(starts)
    # unpack run-end masks to a 0/1 (B, K, M) occupancy, gram on the MXU
    vs = []
    for lane, mask in enumerate(masks):
        end_mask = jnp.where(is_end, mask, 0)
        width = min(32, m - lane * 32)
        bits = (
            (end_mask[:, None] >> jnp.arange(width, dtype=jnp.int32)) & 1
        )
        vs.append(bits)
    v = jnp.concatenate(vs, axis=1).astype(jnp.float32).reshape(b, k, m)
    return jnp.einsum("bkm,bkn->bmn", v, v).astype(jnp.uint16)


shared_bins_packed, shared_bins_packed_donated = jit_pair(
    _shared_bins_packed,
    static_argnames=("m", "lcap"),
    donate_argnums=(0, 1),
)


def _medoid_select_packed(
    bins: jax.Array,  # (B, K) i32 GLOBAL bins, PRE-SORTED (bin, member)
    member_id: jax.Array,  # (B, K) i32 in [0, m], same order, padding = m
    n_peaks: jax.Array,  # (B, M) i32 raw per-member peak counts
    member_mask: jax.Array,  # (B, M) bool
    n_members: jax.Array,  # (B,) i32
    m: int,
    lcap: int | None = None,
) -> jax.Array:
    """Winning medoid member index per cluster, selected ON DEVICE.

    Composes ``shared_bins_packed`` with the finalize reduction so D2H
    carries one int32 per cluster instead of the (B, M, M) uint16 count
    matrices — the medoid path's device→host bytes were its largest cost
    on slow links (BENCH r06: 0.68 s of d2h), and the counts were only
    ever reduced to an argmin on the host anyway.

    The math mirrors ``medoid_finalize`` (prescore = shared / min raw
    counts, distance = 1 − prescore, row sum + double-counted diagonal,
    first-minimum argmin) but runs in device f32 rather than host f64.
    Exact ties — identical members, every 2-member cluster — evaluate
    bitwise-identically on both sides and keep the lowest-index winner;
    f32 rounding can flip a winner only when two members' mean distances
    agree to ~1e-6 relative.  ``TpuBackend(medoid_device_select=False)``
    restores the host-f64 finalize if that margin ever matters."""
    shared = _shared_bins_packed(bins, member_id, m, lcap).astype(
        jnp.float32
    )
    n = n_peaks.astype(jnp.float32)
    min_n = jnp.minimum(n[:, :, None], n[:, None, :])
    prescore = jnp.where(
        min_n > 0, shared / jnp.maximum(min_n, 1.0), 0.0
    )
    dist = 1.0 - prescore
    pair_ok = member_mask[:, :, None] & member_mask[:, None, :]
    dist = jnp.where(pair_ok, dist, 0.0)
    diag = jnp.einsum("bii->bi", dist)
    total = (dist.sum(axis=2) + diag) / jnp.maximum(
        n_members.astype(jnp.float32)[:, None], 1.0
    )
    total = jnp.where(member_mask, total, jnp.inf)
    return jnp.argmin(total, axis=1).astype(jnp.int32)


medoid_select_packed, medoid_select_packed_donated = jit_pair(
    _medoid_select_packed,
    static_argnames=("m", "lcap"),
    donate_argnums=(0, 1, 2, 3, 4),
)


def medoid_finalize(
    shared: "np.ndarray",  # (B, M, M) int
    n_peaks: "np.ndarray",  # (B, M) int raw peak counts
    member_mask: "np.ndarray",  # (B, M) bool
    n_members: "np.ndarray",  # (B,) int
) -> "np.ndarray":
    """Host-side float64 finalize: prescore = shared / min(raw counts),
    distance = 1 − prescore, total = row sum + diagonal (the triangular
    fill's double-counted self-distance, ref
    src/most_similar_representative.py:88-100), lowest-index argmin."""
    import numpy as np

    n = n_peaks.astype(np.float64)
    min_n = np.minimum(n[:, :, None], n[:, None, :])
    with np.errstate(invalid="ignore", divide="ignore"):
        prescore = np.where(
            min_n > 0, shared.astype(np.float64) / np.maximum(min_n, 1.0), 0.0
        )
    dist = 1.0 - prescore
    pair_ok = member_mask[:, :, None] & member_mask[:, None, :]
    dist = np.where(pair_ok, dist, 0.0)
    diag = np.einsum("bii->bi", dist)
    total = (dist.sum(axis=2) + diag) / np.maximum(
        n_members.astype(np.float64)[:, None], 1.0
    )
    total = np.where(member_mask, total, np.inf)
    return np.argmin(total, axis=1).astype(np.int32)


# ---------------------------------------------------------------------------
# Binned cosine — packed layout
# ---------------------------------------------------------------------------

def _cosine_packed_cluster(
    rep_bins: jax.Array,  # (Pr,) i32 NON-DECREASING, sentinel = SENT last
    rep_int: jax.Array,  # (Pr,) f32, same order, 0 where invalid
    rep_edges: jax.Array,  # () i32
    mem_bins: jax.Array,  # (K,) i32 sorted by (member, bin), sentinel = SENT
    mem_int: jax.Array,  # (K,) f32, same order
    mem_member: jax.Array,  # (K,) i32 sorted member ids, padding = m (last)
    mem_edges: jax.Array,  # (M,) i32 per-member edge counts
    member_mask: jax.Array,  # (M,) bool
    n_members: jax.Array,  # () i32
    m: int,
):
    """All (representative, member) cosines of one cluster from packed peaks.

    Per-bin algebra instead of per-pair grids: per-(member, bin) intensity
    sums on member peaks PRE-SORTED by (member, bin) on the host; per-bin
    rep sums (rep pre-sorted by bin) with a prefix of squared run totals;
    then each member's dot/norms are segment reductions with an O(log Pr)
    searchsorted lookup of the rep per-bin sum.  No sort runs on device —
    TPU sorts were the dominant kernel cost; the host lexsorts at prep
    time.  The pair's grid-edge cut (ref src/benchmark.py:20-22: bins
    beyond the pair's last edge are excluded) becomes a per-member cutoff
    ``max(rep_edges, mem_edges[m])-2`` applied to member contributions
    directly and to the rep norm via the prefix array.  Device output is
    just the (M,) cosines.
    """
    from specpride_tpu.ops import segments as sg

    sent = jnp.int32(2**30)
    pr = rep_bins.shape[0]
    k = mem_bins.shape[0]

    # --- rep side: per-bin run totals via segmented scan (scatter-free —
    # TPU scatter-adds with duplicate indices serialize) + prefix of
    # squared totals
    rb = rep_bins
    ri = rep_int
    r_starts = sg.run_starts(rb)
    (r_scan,) = sg.seg_scan(
        r_starts, (jnp.where(rb < sent, ri, 0.0),), pr
    )
    r_last = sg.run_ends(r_starts)
    r_sq_contrib = jnp.where(r_last & (rb < sent), r_scan * r_scan, 0.0)
    r_sq_prefix = jnp.cumsum(r_sq_contrib)  # inclusive, in sorted-bin order

    # --- member side: already sorted by (member, bin) host-side
    sb = mem_bins
    si = mem_int
    sm = mem_member

    cutoff = jnp.maximum(rep_edges, mem_edges) - 2  # (M,) last includable bin
    cut_at = cutoff[jnp.clip(sm, 0, m - 1)]
    ok = (sm < m) & (sb < sent) & (sb <= cut_at)

    m_starts = sg.run_starts2(sm, sb)
    (m_scan,) = sg.seg_scan(m_starts, (jnp.where(ok, si, 0.0),), k)
    is_last = sg.run_ends(m_starts)

    # rep per-bin sum lookup for each member run: the LAST element of the
    # matching rep run holds the run total in the scan
    pos = jnp.searchsorted(rb, sb, side="right") - 1
    pos_c = jnp.clip(pos, 0, pr - 1)
    rep_hit = (rb[pos_c] == sb) & (sb < sent)
    rep_val = jnp.where(rep_hit, r_scan[pos_c], 0.0)

    # per-member dot/norm: contributions at run ends, summed by a
    # member-segmented scan and read at each member's last element, then
    # placed densely by a tiny (M,)-unique scatter
    contrib_ok = is_last & ok
    run_tot = jnp.where(is_last, m_scan, 0.0)
    sm_starts = sg.run_starts(sm)
    dot_scan, norm_scan = sg.seg_scan(
        sm_starts,
        (
            jnp.where(contrib_ok, run_tot * rep_val, 0.0),
            jnp.where(contrib_ok, run_tot * run_tot, 0.0),
        ),
        k,
    )
    # NOTE: midx is NOT sorted (the dropped m-slot interleaves with real
    # member ids), so no indices_are_sorted hint — TPU miscompiles on a
    # false claim.  Real indices are unique; the m-slot collisions are
    # discarded by the [:m] slice.
    mem_end = sg.run_ends(sm_starts)
    midx = jnp.where(mem_end & (sm < m), sm, m)
    dots = jnp.zeros((m + 1,), jnp.float32).at[midx].set(dot_scan)[:m]
    norms = jnp.zeros((m + 1,), jnp.float32).at[midx].set(norm_scan)[:m]

    # rep norm per member: prefix of squared run totals up to the cutoff
    npos = jnp.searchsorted(rb, cutoff + 1, side="left")  # first bin > cutoff
    rep_norm = jnp.where(
        npos > 0, r_sq_prefix[jnp.clip(npos - 1, 0, pr - 1)], 0.0
    )

    okc = (norms > 0) & (rep_norm > 0)
    cos = jnp.where(
        okc, dots / jnp.sqrt(jnp.maximum(norms * rep_norm, 1e-30)), 0.0
    )
    cos = jnp.where(member_mask, cos, 0.0)
    mean = jnp.sum(cos) / jnp.maximum(n_members.astype(jnp.float32), 1.0)
    return mean, cos


def _cosine_flat(
    rkey: jax.Array,  # (Nr,) i32 row*shift+bin, ascending; sentinel tail
    rint: jax.Array,  # (Nr,) f32, same order
    mkey: jax.Array,  # (N,) i32 row*shift+bin per member peak, sorted by
    #   (row, member, bin); sentinel tail
    mint: jax.Array,  # (N,) f32, already 0 where the peak fails the pair's
    #   edge cutoff (the host gates it — it knows both edge tables)
    spec_elem: jax.Array,  # (N,) i32 chunk-local spectrum id per peak,
    #   non-decreasing; padding tail maps to the fill spectrum
    pos: jax.Array,  # (N,) i32 host searchsorted(rkey, mkey, right) - 1 —
    #   the LAST element of the matching rep run (or a non-matching
    #   element when the bin is absent); -1 clipped by the kernel
    spec_offsets: jax.Array,  # (s_pad + 1,) i32 peak extents per spectrum;
    #   fill entries repeat n_pad (zero-length extents)
    spec_row: jax.Array,  # (s_pad,) i32 chunk-local row per spectrum,
    #   non-decreasing; fill = rows_cap - 1
    npos: jax.Array,  # (s_pad,) i32 host searchsorted of each spectrum's
    #   rep-norm cutoff key into rkey
    rep_offsets: jax.Array,  # (rows_cap + 1,) i32 rep extents per row
    row_spec_offsets: jax.Array,  # (rows_cap + 1,) i32 spectrum extents/row
    n_members: jax.Array,  # (rows_cap,) i32
    shift: int,
    l_rep: int,  # pow2 >= longest same-bin run within one rep
    l_row: int,  # pow2 >= longest rep row (peaks per representative)
    l_spec: int,  # pow2 >= most peaks in one member spectrum
    l_mem: int,  # pow2 >= longest same-(spectrum, bin) member run
    l_members: int,  # pow2 >= most spectra in one row (cluster members)
):
    """Flat zero-padding rep-vs-members binned cosine (see
    ``cosine_packed`` for the per-bin algebra; this is the same math over
    ONE flat peak axis for the whole batch), built entirely on
    ``ops.segments`` scans — no scatter anywhere (TPU scatter-adds with
    duplicate indices serialize; the segment_sum formulation this replaces
    spent ~140 ms per call at 4M peaks).

    Anything that would make XLA materialise quadratic traffic stays on
    the host instead: gathers from small per-spectrum tables with
    million-element index vectors lower to one-hot matmuls on TPU (a
    measured 84 GB of HBM traffic for one chunk), and ``searchsorted``'s
    scan loop serialises — so the host ships per-peak composite keys,
    edge-gated intensities, spectrum ids and rep-lookup positions outright
    (H2D runs at GB/s here; D2H at ~25 MB/s is the link to protect, and
    this kernel returns one f32 per cluster).  Per-spectrum dot/norm
    totals are segmented-scan values read at each spectrum's last element;
    the rep-norm prefix is segmented per ROW (never a global f32 cumsum —
    a 4M-element prefix costs ~3 decimal digits); per-row member sums are
    one more scan over the spectrum axis."""
    from specpride_tpu.ops import segments as sg

    sent = jnp.int32(2**31 - 1)
    nr = rkey.shape[0]
    n = mkey.shape[0]
    rows_cap = n_members.shape[0]
    s_pad = spec_row.shape[0]

    # --- rep side: per-bin run totals (short seg_scan: runs <= l_rep)
    rvalid = rkey != sent
    r_starts = sg.run_starts(rkey)
    (r_scan,) = sg.seg_scan(r_starts, (jnp.where(rvalid, rint, 0.0),), l_rep)
    r_ends = sg.run_ends(r_starts)
    r_sq = jnp.where(r_ends & rvalid, r_scan * r_scan, 0.0)
    # per-row squared-total prefix, segmented per ROW: a block-cumsum
    # reconstruction here would subtract prefixes shared with other rows
    # in the block and cancel catastrophically when rows differ in
    # intensity scale (cosines wrong by up to 0.7 absolute in the
    # mixed-scale repro) — scans confine fp error to the row itself
    row_of_rep = jnp.clip(rkey // jnp.int32(shift), 0, rows_cap - 1)
    row_starts_r = sg.run_starts(jnp.where(rvalid, row_of_rep, rows_cap))
    (r_sq_scan,) = sg.seg_scan(row_starts_r, (r_sq,), l_row)

    # --- member side: (spectrum, bin) runs over host-shipped channels
    valid = mkey != sent
    m_starts = sg.run_starts2(spec_elem, mkey)
    m_ends = sg.run_ends(m_starts)
    (m_scan,) = sg.seg_scan(m_starts, (mint,), l_mem)

    # rep per-bin total for each member peak: the host ships
    # ``searchsorted(rkey, mkey, side='right') - 1`` — the LAST element of
    # the matching rep run when the bin is present, where the segmented
    # scan value IS the run total (exact for any run length, no walk)
    pos_c = jnp.clip(pos, 0, nr - 1)
    rep_hit = (rkey[pos_c] == mkey) & valid
    rep_val = jnp.where(rep_hit, r_scan[pos_c], 0.0)

    # per-spectrum dot/norm: contributions at member-run ends, summed by a
    # spectrum-segmented scan (fp error confined to the spectrum — spectra
    # of wildly different intensity scale share blocks in real data) and
    # read at each spectrum's last element
    run_sum_at_end = jnp.where(m_ends, m_scan, 0.0)
    spec_starts = sg.run_starts(spec_elem)
    (dot_scan, norm_scan) = sg.seg_scan(
        spec_starts,
        (run_sum_at_end * rep_val, run_sum_at_end * run_sum_at_end),
        l_spec,
    )
    spec_last = jnp.clip(spec_offsets[1:] - 1, 0, n - 1)  # (s_pad,)
    nonempty = spec_offsets[1:] > spec_offsets[:-1]
    dots = jnp.where(nonempty, dot_scan[spec_last], 0.0)
    norms = jnp.where(nonempty, norm_scan[spec_last], 0.0)

    # rep norm per spectrum: row-segmented squared prefix at the cutoff
    row_start = rep_offsets[spec_row]
    has_prefix = npos > row_start
    rep_norm = jnp.where(
        has_prefix, r_sq_scan[jnp.clip(npos - 1, 0, nr - 1)], 0.0
    )

    okc = (norms > 0) & (rep_norm > 0)
    cos = jnp.where(
        okc, dots / jnp.sqrt(jnp.maximum(norms * rep_norm, 1e-30)), 0.0
    )

    # per-row mean over the spectrum axis (spectra sorted by row; member
    # count from the host so zero-peak members still weigh the mean)
    srow_starts = sg.run_starts(spec_row)
    (cos_scan,) = sg.seg_scan(srow_starts, (cos,), min(l_members, s_pad))
    row_last = jnp.clip(row_spec_offsets[1:] - 1, 0, s_pad - 1)
    row_has = row_spec_offsets[1:] > row_spec_offsets[:-1]
    row_sum = jnp.where(row_has, cos_scan[row_last], 0.0)
    return row_sum / jnp.maximum(n_members.astype(jnp.float32), 1.0)


cosine_flat, cosine_flat_donated = jit_pair(
    _cosine_flat,
    static_argnames=(
        "shift", "l_rep", "l_row", "l_spec", "l_mem", "l_members"
    ),
    donate_argnums=tuple(range(12)),
)


def _cosine_packed(
    rep_bins: jax.Array,  # (B, Pr) i32
    rep_int: jax.Array,  # (B, Pr) f32
    rep_edges: jax.Array,  # (B,) i32
    mem_bins: jax.Array,  # (B, K) i32
    mem_int: jax.Array,  # (B, K) f32
    mem_member: jax.Array,  # (B, K) i32
    mem_edges: jax.Array,  # (B, M) i32
    member_mask: jax.Array,  # (B, M) bool
    n_members: jax.Array,  # (B,) i32
    m: int,
):
    """Packed rep-vs-members binned cosine (ref src/benchmark.py:31-38).
    Rep rows must be pre-sorted by bin and member rows by (member, bin)
    with the member channel already padding-mapped to ``m`` (the backend's
    host prep does both).  Returns ((B,) mean cosine, (B, M) pair
    cosines) — the only D2H bytes."""
    return jax.vmap(
        lambda a, b, c, d, e, f, g, h, i: _cosine_packed_cluster(
            a, b, c, d, e, f, g, h, i, m
        )
    )(
        rep_bins, rep_int, rep_edges, mem_bins, mem_int, mem_member,
        mem_edges, member_mask, n_members,
    )


cosine_packed, cosine_packed_donated = jit_pair(
    _cosine_packed,
    static_argnames=("m",),
    donate_argnums=tuple(range(9)),
)
