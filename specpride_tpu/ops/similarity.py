"""K2: similarity device kernels — medoid selection and binned cosine.

Medoid (most-similar representative).  TPU-native replacement for the O(n²)
Python loop that crosses into OpenMS C++ per pair at ref
src/most_similar_representative.py:91-93: all members of a cluster are binned
once into a dense 0/1 occupancy matrix ``O`` (member × grid), and the shared
occupied-bin counts for EVERY pair come from one batched gram matmul
``S = O @ O.T`` on the MXU.  xcorr prescore = S / min(raw peak counts)
(the pyOpenMS ``XQuestScores::xCorrelationPrescore`` capability, ref :15),
distance = 1 − prescore, and the reference's total-distance semantics —
upper-triangular fill including the diagonal, summed row + column, so the
self-distance counts twice (ref :88-100) — become row-sum + diagonal.
Tie-break: lowest index wins (ref :103-110) = ``jnp.argmin`` first-minimum.

Binned cosine (quality metric, ref src/benchmark.py:11-38).  The reference
grid is ~0.005 Da over [−space/2, max m/z of the pair) — ~400k bins, far too
wasteful to materialise per pair.  Instead each (representative, member) pair
is scored with a sort/segment kernel: concatenate the two spectra's
(precomputed f64) bin ids as a two-channel value array, one stable sort
groups equal bins, segmented sums give per-bin intensity totals for each
channel, and dot/norms are plain reductions — O(P log P) per pair with no
dense grid.  ``sum(segA * segB)`` is exactly ``vecA @ vecB`` of the dense
grid vectors because bins occupied by only one channel contribute zero.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import CosineConfig, MedoidConfig


# ---------------------------------------------------------------------------
# Medoid
# ---------------------------------------------------------------------------

_SENT = jnp.int32(2**30)  # padding sentinel for global bin ids


@functools.partial(jax.jit, static_argnames=("m",))
def shared_bins_packed(
    bins: jax.Array,  # (B, K) i32 GLOBAL bins, PRE-SORTED (bin, member)
    member_id: jax.Array,  # (B, K) i32 in [0, m], same order, padding = m
    m: int,
) -> jax.Array:
    """(B, M, M) shared occupied-bin counts for every member pair.

    Sort/segment formulation — no dense (M, grid) occupancy and no scatter
    (TPU scatters serialize; the round-1 dense-grid kernel spent its time
    there and its data-dependent ``grid`` static arg recompiled per batch).
    Rows arrive PRE-SORTED by (bin, member) from the host (device sorts
    were the dominant kernel cost); the first element of each
    (bin, member) run contributes 1 to a runs×members occupancy ``V``
    built with ONE sorted ``segment_sum`` (segment id = bin_run * m +
    member, non-decreasing by construction), and all pairwise counts come
    from the batched gram matmul ``Vᵀ @ V`` on the MXU.  Bin ids are
    global grid positions (``floor(mz / bin_size)`` in f64 on the host) —
    pairwise intersections don't care about a per-cluster origin, so no
    span/rel-bin pass exists any more.  Counts return as uint16: D2H bytes
    are the bottleneck on tunneled hosts, and counts are bounded by
    per-member peak counts (the driver asserts < 2**16)."""

    def one(sb, sm):
        k = sb.shape[0]
        ok = (sm < m) & (sb < _SENT)
        new_bin = jnp.concatenate(
            [jnp.ones((1,), jnp.int32), (sb[1:] != sb[:-1]).astype(jnp.int32)]
        )
        bin_run = jnp.cumsum(new_bin) - 1
        first_of_mb = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (sb[1:] != sb[:-1]) | (sm[1:] != sm[:-1]),
            ]
        )
        val = jnp.where(ok & first_of_mb, 1.0, 0.0)
        seg = bin_run * m + jnp.clip(sm, 0, m - 1)
        occ = jax.ops.segment_sum(
            val, seg, num_segments=k * m, indices_are_sorted=True
        )
        v = occ.reshape(k, m)
        return (v.T @ v).astype(jnp.uint16)  # MXU

    return jax.vmap(one)(bins, member_id)


def medoid_finalize(
    shared: "np.ndarray",  # (B, M, M) int
    n_peaks: "np.ndarray",  # (B, M) int raw peak counts
    member_mask: "np.ndarray",  # (B, M) bool
    n_members: "np.ndarray",  # (B,) int
) -> "np.ndarray":
    """Host-side float64 finalize: prescore = shared / min(raw counts),
    distance = 1 − prescore, total = row sum + diagonal (the triangular
    fill's double-counted self-distance, ref
    src/most_similar_representative.py:88-100), lowest-index argmin."""
    import numpy as np

    n = n_peaks.astype(np.float64)
    min_n = np.minimum(n[:, :, None], n[:, None, :])
    with np.errstate(invalid="ignore", divide="ignore"):
        prescore = np.where(
            min_n > 0, shared.astype(np.float64) / np.maximum(min_n, 1.0), 0.0
        )
    dist = 1.0 - prescore
    pair_ok = member_mask[:, :, None] & member_mask[:, None, :]
    dist = np.where(pair_ok, dist, 0.0)
    diag = np.einsum("bii->bi", dist)
    total = (dist.sum(axis=2) + diag) / np.maximum(
        n_members.astype(np.float64)[:, None], 1.0
    )
    total = np.where(member_mask, total, np.inf)
    return np.argmin(total, axis=1).astype(np.int32)


# ---------------------------------------------------------------------------
# Binned cosine — packed layout
# ---------------------------------------------------------------------------

def _cosine_packed_cluster(
    rep_bins: jax.Array,  # (Pr,) i32 NON-DECREASING, sentinel = SENT last
    rep_int: jax.Array,  # (Pr,) f32, same order, 0 where invalid
    rep_edges: jax.Array,  # () i32
    mem_bins: jax.Array,  # (K,) i32 sorted by (member, bin), sentinel = SENT
    mem_int: jax.Array,  # (K,) f32, same order
    mem_member: jax.Array,  # (K,) i32 sorted member ids, padding = m (last)
    mem_edges: jax.Array,  # (M,) i32 per-member edge counts
    member_mask: jax.Array,  # (M,) bool
    n_members: jax.Array,  # () i32
    m: int,
):
    """All (representative, member) cosines of one cluster from packed peaks.

    Per-bin algebra instead of per-pair grids: per-(member, bin) intensity
    sums on member peaks PRE-SORTED by (member, bin) on the host; per-bin
    rep sums (rep pre-sorted by bin) with a prefix of squared run totals;
    then each member's dot/norms are segment reductions with an O(log Pr)
    searchsorted lookup of the rep per-bin sum.  No sort runs on device —
    TPU sorts were the dominant kernel cost; the host lexsorts at prep
    time.  The pair's grid-edge cut (ref src/benchmark.py:20-22: bins
    beyond the pair's last edge are excluded) becomes a per-member cutoff
    ``max(rep_edges, mem_edges[m])-2`` applied to member contributions
    directly and to the rep norm via the prefix array.  Device output is
    just the (M,) cosines.
    """
    sent = jnp.int32(2**30)
    pr = rep_bins.shape[0]
    k = mem_bins.shape[0]

    # --- rep side: per-bin sums + prefix of squared run totals
    rb = rep_bins
    ri = rep_int
    r_new = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (rb[1:] != rb[:-1]).astype(jnp.int32)]
    )
    r_seg = jnp.cumsum(r_new)
    r_sum_per_seg = jax.ops.segment_sum(
        jnp.where(rb < sent, ri, 0.0), r_seg, num_segments=pr,
        indices_are_sorted=True,
    )
    r_sum_at = r_sum_per_seg[r_seg]  # run total broadcast to every element
    r_last = jnp.concatenate([rb[:-1] != rb[1:], jnp.ones((1,), bool)])
    r_sq_contrib = jnp.where(r_last & (rb < sent), r_sum_at * r_sum_at, 0.0)
    r_sq_prefix = jnp.cumsum(r_sq_contrib)  # inclusive, in sorted-bin order

    # --- member side: already sorted by (member, bin) host-side
    sb = mem_bins
    si = mem_int
    sm = mem_member

    cutoff = jnp.maximum(rep_edges, mem_edges) - 2  # (M,) last includable bin
    cut_at = cutoff[jnp.clip(sm, 0, m - 1)]
    ok = (sm < m) & (sb < sent) & (sb <= cut_at)

    run_new = jnp.concatenate(
        [
            jnp.zeros((1,), jnp.int32),
            ((sb[1:] != sb[:-1]) | (sm[1:] != sm[:-1])).astype(jnp.int32),
        ]
    )
    run_seg = jnp.cumsum(run_new)
    run_sum = jax.ops.segment_sum(
        jnp.where(ok, si, 0.0), run_seg, num_segments=k, indices_are_sorted=True
    )
    run_sum_at = run_sum[run_seg]
    is_last = jnp.concatenate(
        [(sb[:-1] != sb[1:]) | (sm[:-1] != sm[1:]), jnp.ones((1,), bool)]
    )

    # rep per-bin sum lookup for each member run
    pos = jnp.searchsorted(rb, sb, side="left")
    pos_c = jnp.clip(pos, 0, pr - 1)
    rep_hit = (rb[pos_c] == sb) & (sb < sent)
    rep_val = jnp.where(rep_hit, r_sum_per_seg[r_seg[pos_c]], 0.0)

    contrib_ok = is_last & ok
    dots = jax.ops.segment_sum(
        jnp.where(contrib_ok, run_sum_at * rep_val, 0.0),
        sm,
        num_segments=m + 1,
        indices_are_sorted=True,
    )[:m]
    norms = jax.ops.segment_sum(
        jnp.where(contrib_ok, run_sum_at * run_sum_at, 0.0),
        sm,
        num_segments=m + 1,
        indices_are_sorted=True,
    )[:m]

    # rep norm per member: prefix of squared run totals up to the cutoff
    npos = jnp.searchsorted(rb, cutoff + 1, side="left")  # first bin > cutoff
    rep_norm = jnp.where(
        npos > 0, r_sq_prefix[jnp.clip(npos - 1, 0, pr - 1)], 0.0
    )

    okc = (norms > 0) & (rep_norm > 0)
    cos = jnp.where(
        okc, dots / jnp.sqrt(jnp.maximum(norms * rep_norm, 1e-30)), 0.0
    )
    cos = jnp.where(member_mask, cos, 0.0)
    mean = jnp.sum(cos) / jnp.maximum(n_members.astype(jnp.float32), 1.0)
    return mean, cos


@functools.partial(jax.jit, static_argnames=("mcap", "shift"))
def cosine_flat(
    rkey: jax.Array,  # (Nr,) i32 row*shift+bin, ascending; sentinel tail
    rint: jax.Array,  # (Nr,) f32, same order
    rep_offsets: jax.Array,  # (rows_cap + 1,) i32 rep extents per row
    rep_edges: jax.Array,  # (rows_cap,) i32
    cbin: jax.Array,  # (N,) i32 cosine bins sorted by (row, member, bin)
    mint: jax.Array,  # (N,) f32, same order
    spec_offsets: jax.Array,  # (S + 1,) i32 peak extents per spectrum
    spec_gmem: jax.Array,  # (S + 1,) i32 row*mcap+member per spectrum;
    #   entry S is the rows_cap*mcap sentinel for the padding tail
    mem_edges: jax.Array,  # (rows_cap * mcap,) i32 per-(row, member)
    n_members: jax.Array,  # (rows_cap,) i32
    mcap: int,
    shift: int,
):
    """Flat zero-padding rep-vs-members binned cosine (see
    ``cosine_packed`` for the per-bin algebra; this is the same math over
    ONE flat peak axis for the whole batch).  Composite int32 keys
    (``row * shift + bin``) make rep lookups a single global searchsorted
    and member runs globally unique — no vmap, no per-row padding.  The
    per-peak (row, member) channel is DERIVED on device from the tiny
    per-spectrum extent table (H2D bytes are the bottleneck; shipping it
    per peak would cost 4 B/peak).  The per-row rep-norm prefix is a
    global cumsum differenced at row starts.  Returns the (rows_cap,)
    mean cosines — the only D2H bytes."""
    sent = jnp.int32(2**31 - 1)
    nr = rkey.shape[0]
    n = cbin.shape[0]
    rows_cap = rep_edges.shape[0]
    s = spec_gmem.shape[0] - 1

    # derive per-peak (row, member) + composite bin key on device
    spec_of_elem = (
        jnp.searchsorted(
            spec_offsets, jnp.arange(n, dtype=jnp.int32), side="right"
        )
        - 1
    )
    gmem = spec_gmem[jnp.clip(spec_of_elem, 0, s)]
    valid0 = cbin < sent
    mkey_row = jnp.clip(gmem // mcap, 0, rows_cap - 1)
    # dead-branch overflow of the multiply is discarded by the where
    mkey = jnp.where(
        valid0, mkey_row * jnp.int32(shift) + cbin, sent
    )

    # --- rep side: per-bin sums + global prefix of squared run totals
    rvalid = rkey < sent
    r_new = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (rkey[1:] != rkey[:-1]).astype(jnp.int32)]
    )
    r_seg = jnp.cumsum(r_new)
    r_sum_per_seg = jax.ops.segment_sum(
        jnp.where(rvalid, rint, 0.0), r_seg, num_segments=nr,
        indices_are_sorted=True,
    )
    r_sum_at = r_sum_per_seg[r_seg]
    r_last = jnp.concatenate([rkey[:-1] != rkey[1:], jnp.ones((1,), bool)])
    r_sq_contrib = jnp.where(r_last & rvalid, r_sum_at * r_sum_at, 0.0)
    r_sq_prefix = jnp.cumsum(r_sq_contrib)

    # --- member side: runs of (row, member, bin) = (gmem, mkey) pairs
    valid = mkey < sent
    row_of_elem = jnp.clip(gmem // mcap, 0, rows_cap - 1)
    gm_c = jnp.clip(gmem, 0, rows_cap * mcap - 1)
    cut = jnp.maximum(rep_edges[row_of_elem], mem_edges[gm_c]) - 2
    cutkey = row_of_elem.astype(jnp.int32) * jnp.int32(shift) + cut
    ok = valid & (mkey <= cutkey)

    run_new = jnp.concatenate(
        [
            jnp.zeros((1,), jnp.int32),
            ((mkey[1:] != mkey[:-1]) | (gmem[1:] != gmem[:-1])).astype(
                jnp.int32
            ),
        ]
    )
    run_seg = jnp.cumsum(run_new)
    run_sum = jax.ops.segment_sum(
        jnp.where(ok, mint, 0.0), run_seg, num_segments=n,
        indices_are_sorted=True,
    )
    run_sum_at = run_sum[run_seg]
    is_last = jnp.concatenate(
        [(mkey[:-1] != mkey[1:]) | (gmem[:-1] != gmem[1:]), jnp.ones((1,), bool)]
    )

    pos = jnp.searchsorted(rkey, mkey, side="left")
    pos_c = jnp.clip(pos, 0, nr - 1)
    rep_hit = (rkey[pos_c] == mkey) & valid
    rep_val = jnp.where(rep_hit, r_sum_per_seg[r_seg[pos_c]], 0.0)

    contrib = is_last & ok
    seg_ids = jnp.where(valid, gm_c, rows_cap * mcap)
    dots = jax.ops.segment_sum(
        jnp.where(contrib, run_sum_at * rep_val, 0.0),
        seg_ids,
        num_segments=rows_cap * mcap + 1,
        indices_are_sorted=True,
    )[:-1]
    norms = jax.ops.segment_sum(
        jnp.where(contrib, run_sum_at * run_sum_at, 0.0),
        seg_ids,
        num_segments=rows_cap * mcap + 1,
        indices_are_sorted=True,
    )[:-1]

    # rep norm per (row, member): prefix difference over the row's window
    row_ids = jnp.repeat(
        jnp.arange(rows_cap, dtype=jnp.int32), mcap
    )  # (rows_cap*mcap,)
    pair_cut = (
        jnp.maximum(rep_edges[row_ids], mem_edges) - 2
    )  # (rows_cap*mcap,)
    npos = jnp.searchsorted(
        rkey, row_ids * jnp.int32(shift) + pair_cut + 1, side="left"
    )
    upto = jnp.where(npos > 0, r_sq_prefix[jnp.clip(npos - 1, 0, nr - 1)], 0.0)
    row_start = rep_offsets[row_ids]
    base = jnp.where(
        row_start > 0, r_sq_prefix[jnp.clip(row_start - 1, 0, nr - 1)], 0.0
    )
    rep_norm = jnp.maximum(upto - base, 0.0)

    okc = (norms > 0) & (rep_norm > 0)
    cos = jnp.where(
        okc, dots / jnp.sqrt(jnp.maximum(norms * rep_norm, 1e-30)), 0.0
    )
    member_ids = jnp.tile(jnp.arange(mcap, dtype=jnp.int32), rows_cap)
    mask = member_ids < n_members[row_ids]
    cos = jnp.where(mask, cos, 0.0).reshape(rows_cap, mcap)
    return jnp.sum(cos, axis=1) / jnp.maximum(
        n_members.astype(jnp.float32), 1.0
    )


@functools.partial(jax.jit, static_argnames=("m",))
def cosine_packed(
    rep_bins: jax.Array,  # (B, Pr) i32
    rep_int: jax.Array,  # (B, Pr) f32
    rep_edges: jax.Array,  # (B,) i32
    mem_bins: jax.Array,  # (B, K) i32
    mem_int: jax.Array,  # (B, K) f32
    mem_member: jax.Array,  # (B, K) i32
    mem_edges: jax.Array,  # (B, M) i32
    member_mask: jax.Array,  # (B, M) bool
    n_members: jax.Array,  # (B,) i32
    m: int,
):
    """Packed rep-vs-members binned cosine (ref src/benchmark.py:31-38).
    Rep rows must be pre-sorted by bin and member rows by (member, bin)
    with the member channel already padding-mapped to ``m`` (the backend's
    host prep does both).  Returns ((B,) mean cosine, (B, M) pair
    cosines) — the only D2H bytes."""
    return jax.vmap(
        lambda a, b, c, d, e, f, g, h, i: _cosine_packed_cluster(
            a, b, c, d, e, f, g, h, i, m
        )
    )(
        rep_bins, rep_int, rep_edges, mem_bins, mem_int, mem_member,
        mem_edges, member_mask, n_members,
    )
