"""K2: similarity device kernels — medoid selection and binned cosine.

Medoid (most-similar representative).  TPU-native replacement for the O(n²)
Python loop that crosses into OpenMS C++ per pair at ref
src/most_similar_representative.py:91-93: all members of a cluster are binned
once into a dense 0/1 occupancy matrix ``O`` (member × grid), and the shared
occupied-bin counts for EVERY pair come from one batched gram matmul
``S = O @ O.T`` on the MXU.  xcorr prescore = S / min(raw peak counts)
(the pyOpenMS ``XQuestScores::xCorrelationPrescore`` capability, ref :15),
distance = 1 − prescore, and the reference's total-distance semantics —
upper-triangular fill including the diagonal, summed row + column, so the
self-distance counts twice (ref :88-100) — become row-sum + diagonal.
Tie-break: lowest index wins (ref :103-110) = ``jnp.argmin`` first-minimum.

Binned cosine (quality metric, ref src/benchmark.py:11-38).  The reference
grid is ~0.005 Da over [−space/2, max m/z of the pair) — ~400k bins, far too
wasteful to materialise per pair.  Instead each (representative, member) pair
is scored with a sort/segment kernel: concatenate the two spectra's
(precomputed f64) bin ids as a two-channel value array, one stable sort
groups equal bins, segmented sums give per-bin intensity totals for each
channel, and dot/norms are plain reductions — O(P log P) per pair with no
dense grid.  ``sum(segA * segB)`` is exactly ``vecA @ vecB`` of the dense
grid vectors because bins occupied by only one channel contribute zero.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import CosineConfig, MedoidConfig


# ---------------------------------------------------------------------------
# Medoid
# ---------------------------------------------------------------------------

_SENT = jnp.int32(2**30)  # padding sentinel for global bin ids


@functools.partial(jax.jit, static_argnames=("m",))
def shared_bins_packed(
    bins: jax.Array,  # (B, K) i32 GLOBAL f64-quantized bins, sentinel 2**30
    member_id: jax.Array,  # (B, K) i32, -1 = padding
    m: int,
) -> jax.Array:
    """(B, M, M) shared occupied-bin counts for every member pair.

    Sort/segment formulation — no dense (M, grid) occupancy and no scatter
    (TPU scatters serialize; the round-1 dense-grid kernel spent its time
    there and its data-dependent ``grid`` static arg recompiled per batch).
    Peaks sort by (bin, member); the first element of each (bin, member) run
    contributes 1 to a runs×members occupancy ``V`` built with ONE sorted
    ``segment_sum`` (segment id = bin_run * m + member, non-decreasing by
    construction), and all pairwise counts come from the batched gram matmul
    ``Vᵀ @ V`` on the MXU.  Bin ids are global grid positions
    (``floor(mz / bin_size)`` in f64 on the host) — pairwise intersections
    don't care about a per-cluster origin, so no span/rel-bin pass exists
    any more.  Counts return as uint16: D2H bytes are the bottleneck on
    tunneled hosts, and counts are bounded by per-member peak counts (the
    driver asserts < 2**16)."""

    def one(b, mid):
        k = b.shape[0]
        mm = jnp.where(mid >= 0, mid, m)  # padding sorts last
        o1 = jnp.argsort(mm, stable=True)
        o2 = jnp.argsort(b[o1], stable=True)
        perm = o1[o2]
        sb = b[perm]
        sm = mm[perm]
        ok = (sm < m) & (sb < _SENT)
        new_bin = jnp.concatenate(
            [jnp.ones((1,), jnp.int32), (sb[1:] != sb[:-1]).astype(jnp.int32)]
        )
        bin_run = jnp.cumsum(new_bin) - 1
        first_of_mb = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (sb[1:] != sb[:-1]) | (sm[1:] != sm[:-1]),
            ]
        )
        val = jnp.where(ok & first_of_mb, 1.0, 0.0)
        seg = bin_run * m + jnp.clip(sm, 0, m - 1)
        occ = jax.ops.segment_sum(
            val, seg, num_segments=k * m, indices_are_sorted=True
        )
        v = occ.reshape(k, m)
        return (v.T @ v).astype(jnp.uint16)  # MXU

    return jax.vmap(one)(bins, member_id)


def medoid_finalize(
    shared: "np.ndarray",  # (B, M, M) int
    n_peaks: "np.ndarray",  # (B, M) int raw peak counts
    member_mask: "np.ndarray",  # (B, M) bool
    n_members: "np.ndarray",  # (B,) int
) -> "np.ndarray":
    """Host-side float64 finalize: prescore = shared / min(raw counts),
    distance = 1 − prescore, total = row sum + diagonal (the triangular
    fill's double-counted self-distance, ref
    src/most_similar_representative.py:88-100), lowest-index argmin."""
    import numpy as np

    n = n_peaks.astype(np.float64)
    min_n = np.minimum(n[:, :, None], n[:, None, :])
    with np.errstate(invalid="ignore", divide="ignore"):
        prescore = np.where(
            min_n > 0, shared.astype(np.float64) / np.maximum(min_n, 1.0), 0.0
        )
    dist = 1.0 - prescore
    pair_ok = member_mask[:, :, None] & member_mask[:, None, :]
    dist = np.where(pair_ok, dist, 0.0)
    diag = np.einsum("bii->bi", dist)
    total = (dist.sum(axis=2) + diag) / np.maximum(
        n_members.astype(np.float64)[:, None], 1.0
    )
    total = np.where(member_mask, total, np.inf)
    return np.argmin(total, axis=1).astype(np.int32)


# ---------------------------------------------------------------------------
# Binned cosine — packed layout
# ---------------------------------------------------------------------------

def _cosine_packed_cluster(
    rep_bins: jax.Array,  # (Pr,) i32, sentinel = SENT for padding
    rep_int: jax.Array,  # (Pr,) f32, 0 where invalid
    rep_edges: jax.Array,  # () i32
    mem_bins: jax.Array,  # (K,) i32, sentinel = SENT
    mem_int: jax.Array,  # (K,) f32
    mem_member: jax.Array,  # (K,) i32, -1 = padding
    mem_edges: jax.Array,  # (M,) i32 per-member edge counts
    member_mask: jax.Array,  # (M,) bool
    n_members: jax.Array,  # () i32
    m: int,
):
    """All (representative, member) cosines of one cluster from packed peaks.

    Per-bin algebra instead of per-pair grids: sort member peaks by
    (member, bin) → per-(member, bin) intensity sums; sort rep peaks by bin
    → per-bin rep sums with a prefix of squared run totals; then each
    member's dot/norms are segment reductions with an O(log Pr)
    searchsorted lookup of the rep per-bin sum.  The pair's grid-edge cut
    (ref src/benchmark.py:20-22: bins beyond the pair's last edge are
    excluded) becomes a per-member cutoff ``max(rep_edges, mem_edges[m])-2``
    applied to member contributions directly and to the rep norm via the
    prefix array.  Device output is just the (M,) cosines.
    """
    sent = jnp.int32(2**30)
    pr = rep_bins.shape[0]
    k = mem_bins.shape[0]

    # --- rep side: per-bin sums + prefix of squared run totals
    r_order = jnp.argsort(rep_bins, stable=True)
    rb = rep_bins[r_order]
    ri = rep_int[r_order]
    r_new = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (rb[1:] != rb[:-1]).astype(jnp.int32)]
    )
    r_seg = jnp.cumsum(r_new)
    r_sum_per_seg = jax.ops.segment_sum(
        jnp.where(rb < sent, ri, 0.0), r_seg, num_segments=pr,
        indices_are_sorted=True,
    )
    r_sum_at = r_sum_per_seg[r_seg]  # run total broadcast to every element
    r_last = jnp.concatenate([rb[:-1] != rb[1:], jnp.ones((1,), bool)])
    r_sq_contrib = jnp.where(r_last & (rb < sent), r_sum_at * r_sum_at, 0.0)
    r_sq_prefix = jnp.cumsum(r_sq_contrib)  # inclusive, in sorted-bin order

    # --- member side: sort by (member, bin) via two stable argsorts
    mm = jnp.where(mem_member >= 0, mem_member, m)  # padding sorts last
    o1 = jnp.argsort(mem_bins, stable=True)
    o2 = jnp.argsort(mm[o1], stable=True)
    perm = o1[o2]
    sb = mem_bins[perm]
    si = mem_int[perm]
    sm = mm[perm]

    cutoff = jnp.maximum(rep_edges, mem_edges) - 2  # (M,) last includable bin
    cut_at = cutoff[jnp.clip(sm, 0, m - 1)]
    ok = (sm < m) & (sb < sent) & (sb <= cut_at)

    run_new = jnp.concatenate(
        [
            jnp.zeros((1,), jnp.int32),
            ((sb[1:] != sb[:-1]) | (sm[1:] != sm[:-1])).astype(jnp.int32),
        ]
    )
    run_seg = jnp.cumsum(run_new)
    run_sum = jax.ops.segment_sum(
        jnp.where(ok, si, 0.0), run_seg, num_segments=k, indices_are_sorted=True
    )
    run_sum_at = run_sum[run_seg]
    is_last = jnp.concatenate(
        [(sb[:-1] != sb[1:]) | (sm[:-1] != sm[1:]), jnp.ones((1,), bool)]
    )

    # rep per-bin sum lookup for each member run
    pos = jnp.searchsorted(rb, sb, side="left")
    pos_c = jnp.clip(pos, 0, pr - 1)
    rep_hit = (rb[pos_c] == sb) & (sb < sent)
    rep_val = jnp.where(rep_hit, r_sum_per_seg[r_seg[pos_c]], 0.0)

    contrib_ok = is_last & ok
    dots = jax.ops.segment_sum(
        jnp.where(contrib_ok, run_sum_at * rep_val, 0.0),
        sm,
        num_segments=m + 1,
        indices_are_sorted=True,
    )[:m]
    norms = jax.ops.segment_sum(
        jnp.where(contrib_ok, run_sum_at * run_sum_at, 0.0),
        sm,
        num_segments=m + 1,
        indices_are_sorted=True,
    )[:m]

    # rep norm per member: prefix of squared run totals up to the cutoff
    npos = jnp.searchsorted(rb, cutoff + 1, side="left")  # first bin > cutoff
    rep_norm = jnp.where(
        npos > 0, r_sq_prefix[jnp.clip(npos - 1, 0, pr - 1)], 0.0
    )

    okc = (norms > 0) & (rep_norm > 0)
    cos = jnp.where(
        okc, dots / jnp.sqrt(jnp.maximum(norms * rep_norm, 1e-30)), 0.0
    )
    cos = jnp.where(member_mask, cos, 0.0)
    mean = jnp.sum(cos) / jnp.maximum(n_members.astype(jnp.float32), 1.0)
    return mean, cos


@functools.partial(jax.jit, static_argnames=("m",))
def cosine_packed(
    rep_bins: jax.Array,  # (B, Pr) i32
    rep_int: jax.Array,  # (B, Pr) f32
    rep_edges: jax.Array,  # (B,) i32
    mem_bins: jax.Array,  # (B, K) i32
    mem_int: jax.Array,  # (B, K) f32
    mem_member: jax.Array,  # (B, K) i32
    mem_edges: jax.Array,  # (B, M) i32
    member_mask: jax.Array,  # (B, M) bool
    n_members: jax.Array,  # (B,) i32
    m: int,
):
    """Packed rep-vs-members binned cosine (ref src/benchmark.py:31-38).
    Returns ((B,) mean cosine, (B, M) pair cosines) — the only D2H bytes."""
    return jax.vmap(
        lambda a, b, c, d, e, f, g, h, i: _cosine_packed_cluster(
            a, b, c, d, e, f, g, h, i, m
        )
    )(
        rep_bins, rep_int, rep_edges, mem_bins, mem_int, mem_member,
        mem_edges, member_mask, n_members,
    )
