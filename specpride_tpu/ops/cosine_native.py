"""ctypes bindings for the multithreaded C++ binned-cosine QC metric
(native/cosine.cpp — see its header for why the mesh-less backend prefers
host work here: the device kernel ships ~16 B per member peak over a
~90 MB/s tunneled link for a handful of FLOPs per byte).

Loading mirrors ``ops.gap_native``: lazy, soft-failing (``available()``
False when unbuilt), reusing the one-shot ``make -C native`` bootstrap."""

from __future__ import annotations

import ctypes
import threading

import numpy as np

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    p = ctypes.POINTER
    lib.pair_cosines_run.restype = ctypes.c_int
    lib.pair_cosines_run.argtypes = [
        p(ctypes.c_double),  # rep_mz
        p(ctypes.c_double),  # rep_int
        p(ctypes.c_int64),  # rep_offsets
        p(ctypes.c_double),  # mem_mz
        p(ctypes.c_double),  # mem_int
        p(ctypes.c_int64),  # spec_offsets
        p(ctypes.c_int64),  # cluster_spec_offsets
        ctypes.c_int64,  # n_clusters
        ctypes.c_double,  # space
        p(ctypes.c_double),  # out_cos
        ctypes.c_int,  # n_threads
    ]
    return lib


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        from specpride_tpu.io.native import load_native

        _lib = load_native("libcosine.so", "SPECPRIDE_COSINE_LIB", _bind)
        _load_failed = _lib is None
        return _lib


def available() -> bool:
    """True when the C++ cosine library is built and loadable."""
    return _load() is not None


def pair_cosines(
    rep_mz: np.ndarray,  # (Pr,) f64, reps contiguous per cluster
    rep_int: np.ndarray,  # (Pr,) f64, same order
    rep_offsets: np.ndarray,  # (C + 1,) i64
    mem_mz: np.ndarray,  # (P,) f64, spectra contiguous, clusters contiguous
    mem_int: np.ndarray,  # (P,) f64, same order
    spec_offsets: np.ndarray,  # (S + 1,) i64 peak extents per spectrum
    cluster_spec_offsets: np.ndarray,  # (C + 1,) i64 spectrum extents/cluster
    space: float,
    n_threads: int = 0,  # 0 = hardware concurrency
) -> np.ndarray:
    """(S,) binned cosine of every member spectrum to its cluster's
    representative (threads released from the GIL — callers may run this
    concurrently with device fetches).  Raises ``RuntimeError`` when the
    library is unavailable (callers guard with ``available()``)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native cosine not built (make -C native)")
    rep_mz = np.ascontiguousarray(rep_mz, dtype=np.float64)
    rep_int = np.ascontiguousarray(rep_int, dtype=np.float64)
    rep_offsets = np.ascontiguousarray(rep_offsets, dtype=np.int64)
    mem_mz = np.ascontiguousarray(mem_mz, dtype=np.float64)
    mem_int = np.ascontiguousarray(mem_int, dtype=np.float64)
    spec_offsets = np.ascontiguousarray(spec_offsets, dtype=np.int64)
    cluster_spec_offsets = np.ascontiguousarray(
        cluster_spec_offsets, dtype=np.int64
    )
    c = cluster_spec_offsets.size - 1
    out = np.zeros(spec_offsets.size - 1, dtype=np.float64)
    dp = ctypes.POINTER(ctypes.c_double)
    ip = ctypes.POINTER(ctypes.c_int64)
    rc = lib.pair_cosines_run(
        rep_mz.ctypes.data_as(dp),
        rep_int.ctypes.data_as(dp),
        rep_offsets.ctypes.data_as(ip),
        mem_mz.ctypes.data_as(dp),
        mem_int.ctypes.data_as(dp),
        spec_offsets.ctypes.data_as(ip),
        cluster_spec_offsets.ctypes.data_as(ip),
        c,
        float(space),
        out.ctypes.data_as(dp),
        int(n_threads),
    )
    if rc != 0:
        raise RuntimeError(f"native cosine failed (rc={rc})")
    return out
