"""ctypes bindings for the multithreaded C++ gap-average consensus
(native/gap_average.cpp — see its header for why this method is host work:
the measured device path lost 14x to numpy over the host link, and a
single numpy thread only ties the per-cluster oracle).

Loading mirrors ``io.native``: lazy, soft-failing (``available()`` False
when unbuilt), reusing the same one-shot ``make -C native`` bootstrap."""

from __future__ import annotations

import ctypes
import threading

import numpy as np

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    p = ctypes.POINTER
    lib.gap_average_run.restype = ctypes.c_int
    lib.gap_average_run.argtypes = [
        p(ctypes.c_double),  # mz
        p(ctypes.c_double),  # intensity
        p(ctypes.c_int64),  # peak_offsets
        p(ctypes.c_int64),  # n_members
        ctypes.c_int64,  # n_clusters
        ctypes.c_double,  # mz_accuracy
        ctypes.c_int,  # tail_mode_reference
        ctypes.c_double,  # min_fraction
        ctypes.c_double,  # dyn_range
        p(ctypes.c_double),  # out_mz
        p(ctypes.c_double),  # out_intensity
        p(ctypes.c_int64),  # out_counts
        ctypes.c_int,  # n_threads
    ]
    return lib


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        from specpride_tpu.io.native import load_native

        _lib = load_native("libgap_average.so", "SPECPRIDE_GAP_LIB", _bind)
        _load_failed = _lib is None
        return _lib


def available() -> bool:
    """True when the C++ gap-average library is built and loadable."""
    return _load() is not None


def gap_average_groups(
    mz: np.ndarray,  # (P,) f64, clusters contiguous
    intensity: np.ndarray,  # (P,) f64, same order
    peak_offsets: np.ndarray,  # (C + 1,) i64
    n_members: np.ndarray,  # (C,) i64
    mz_accuracy: float,
    tail_mode_reference: bool,
    min_fraction: float,
    dyn_range: float,
    n_threads: int = 0,  # 0 = hardware concurrency
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Kept (group m/z, group intensity, per-cluster counts).  Outputs for
    cluster c occupy ``out[peak_offsets[c] : peak_offsets[c] + counts[c]]``
    of the flat buffers.  Raises ``RuntimeError`` when the library is
    unavailable (callers guard with ``available()``)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native gap-average not built (make -C native)")
    mz = np.ascontiguousarray(mz, dtype=np.float64)
    intensity = np.ascontiguousarray(intensity, dtype=np.float64)
    peak_offsets = np.ascontiguousarray(peak_offsets, dtype=np.int64)
    n_members = np.ascontiguousarray(n_members, dtype=np.int64)
    c = peak_offsets.size - 1
    out_mz = np.empty(mz.size, dtype=np.float64)
    out_int = np.empty(mz.size, dtype=np.float64)
    out_counts = np.zeros(c, dtype=np.int64)
    dp = ctypes.POINTER(ctypes.c_double)
    ip = ctypes.POINTER(ctypes.c_int64)
    rc = lib.gap_average_run(
        mz.ctypes.data_as(dp),
        intensity.ctypes.data_as(dp),
        peak_offsets.ctypes.data_as(ip),
        n_members.ctypes.data_as(ip),
        c,
        float(mz_accuracy),
        int(bool(tail_mode_reference)),
        float(min_fraction),
        float(dyn_range),
        out_mz.ctypes.data_as(dp),
        out_int.ctypes.data_as(dp),
        out_counts.ctypes.data_as(ip),
        int(n_threads),
    )
    if rc != 0:
        raise RuntimeError(f"native gap-average failed (rc={rc})")
    return out_mz, out_int, out_counts
