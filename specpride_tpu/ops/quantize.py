"""Host-side float64 m/z quantization → int32 bin indices.

Design note (TPU-first split of responsibilities): TPU device arrays are
float32, but every reference algorithm quantizes m/z on a float64 grid
(``((mz - min)/binsize).astype(int)`` ref src/binning.py:195; ``mz/0.1``
occupancy bins consumed via pyOpenMS at ref
src/most_similar_representative.py:15; ~0.005 Da grid at ref
src/benchmark.py:11-15).  Recomputing those bin indices in float32 on device
would move ~0.5% of peaks across bin boundaries — a silent parity break.

So the f64-sensitive *quantization* happens here on the host (cheap, O(peaks)
numpy), and the device kernels receive int32 bin indices and do all the heavy
reduction work (scatter-add, matmuls, sorts).  Invalid/padded peaks get the
``sentinel`` index (= number of bins), which device scatters drop via
``mode='drop'`` and sorts push past every real bin.
"""

from __future__ import annotations

import numpy as np

from specpride_tpu.config import BinMeanConfig, CosineConfig, MedoidConfig
from specpride_tpu.data.ragged import ClusterBatch


def bin_mean_bins(batch: ClusterBatch, config: BinMeanConfig) -> np.ndarray:
    """(B, M, P) int32 grid-bin indices for the binned-mean consensus.

    Reproduces ref src/binning.py:191-195 in float64: peaks outside
    [min_mz, max_mz) — and padded peaks — map to the sentinel ``n_bins``.
    """
    mz = batch.mz64
    n_bins = config.n_bins
    in_range = (
        (mz >= config.min_mz)
        & (mz < config.max_mz)
        & batch.peak_mask
        & batch.member_mask[:, :, None]
    )
    bins = ((mz - config.min_mz) / config.bin_size).astype(np.int64)
    bins = np.clip(bins, 0, n_bins - 1)
    return np.where(in_range, bins, n_bins).astype(np.int32)


def medoid_bins(
    batch: ClusterBatch, config: MedoidConfig
) -> tuple[np.ndarray, int]:
    """Per-cluster-relative occupancy-bin indices for the medoid kernel.

    Global bin = ``int(mz / bin_size)`` (the xcorr-prescore grid, ref
    src/most_similar_representative.py:15 / numpy oracle
    ``backends.numpy_backend.xcorr_prescore``).  Bins are shifted by each
    cluster's minimum occupied bin so the dense occupancy matrix only spans
    the cluster's m/z range; returns (bins_rel, grid_size) where grid_size is
    the batch-wide max span rounded up to a multiple of 128 (lane-friendly).
    """
    mz = batch.mz64
    valid = batch.peak_mask & batch.member_mask[:, :, None]
    bins = (mz / config.bin_size).astype(np.int64)
    big = np.iinfo(np.int64).max
    per_cluster_min = np.where(valid, bins, big).min(axis=(1, 2))
    per_cluster_min = np.where(
        per_cluster_min == big, 0, per_cluster_min
    )  # all-empty cluster
    rel = bins - per_cluster_min[:, None, None]
    span = int(np.where(valid, rel, -1).max(initial=0)) + 1
    grid = max(128, ((span + 127) // 128) * 128)
    return np.where(valid, rel, grid).astype(np.int32), grid


def cosine_bins(
    mz: np.ndarray, valid: np.ndarray, config: CosineConfig
) -> tuple[np.ndarray, np.ndarray]:
    """Cosine-grid bin indices + per-spectrum edge counts, float64.

    The metric grid (ref src/benchmark.py:11-15) has edges
    ``arange(-mz_space/2, max_mz, mz_space)`` — bin index is therefore
    ``floor((mz + space/2) / space)``, *independent* of the pair's max m/z;
    only the number of edges depends on it.  Returns:

    * ``bins``: same shape as ``mz``, int32, sentinel = INT32_MAX/2 for
      invalid peaks (so they sort last);
    * ``n_edges``: per-spectrum edge count ``len(arange(-s/2, last_mz, s))``
      computed in f64.  A pair's edge count is the max of its two spectra
      (edge count is monotone in last m/z), and peaks in bins
      ``>= n_edges - 1`` fall beyond the pair's last edge and are excluded
      (ref src/benchmark.py:20-22 via scipy binned_statistic range).
    """
    space = config.mz_space
    mzf = mz.astype(np.float64)
    bins = np.floor((mzf + space / 2.0) / space).astype(np.int64)
    sentinel = np.int32(2**30)
    bins = np.where(valid, np.clip(bins, 0, sentinel - 1), sentinel)
    # the reference (and oracle) take the LAST peak's m/z, not the max
    # (``max(a.mz[-1], b.mz[-1])`` ref src/benchmark.py:20 assumes sorted
    # spectra) — reproduce exactly: value at the last valid index
    n_valid = valid.sum(axis=-1)
    last_idx = np.maximum(n_valid - 1, 0)
    last_mz = np.take_along_axis(mzf, last_idx[..., None], axis=-1)[..., 0]
    last_mz = np.where(n_valid > 0, last_mz, -np.inf)
    # numpy arange length: ceil((stop - start) / step)
    n_edges = np.ceil((last_mz + space / 2.0) / space)
    n_edges = np.where(np.isfinite(n_edges), np.maximum(n_edges, 0), 0)
    return bins.astype(np.int32), n_edges.astype(np.int32)
