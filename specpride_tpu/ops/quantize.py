"""Host-side float64 m/z quantization → int32 bin indices.

Design note (TPU-first split of responsibilities): TPU device arrays are
float32, but every reference algorithm quantizes m/z on a float64 grid
(``((mz - min)/binsize).astype(int)`` ref src/binning.py:195; ``mz/0.1``
occupancy bins consumed via pyOpenMS at ref
src/most_similar_representative.py:15; ~0.005 Da grid at ref
src/benchmark.py:11-15).  Recomputing those bin indices in float32 on device
would move ~0.5% of peaks across bin boundaries — a silent parity break.

So the f64-sensitive *quantization* happens here on the host (cheap, O(peaks)
numpy), and the device kernels receive int32 bin indices and do all the heavy
reduction work (scatter-add, matmuls, sorts).  Invalid/padded peaks get the
``sentinel`` index (= number of bins), which device scatters drop via
``mode='drop'`` and sorts push past every real bin.
"""

from __future__ import annotations

import numpy as np

from specpride_tpu.config import (
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)


def gap_segments(
    members, config: GapAverageConfig
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sorted f64 (mz, intensity, segment-id) arrays for one cluster — the
    SINGLE implementation of the reference's gap-grouping semantics, shared
    by the numpy oracle (``backends.numpy_backend.gap_average_consensus``)
    and the device pack path (``data.packed.pack_bucketize_gap``) so the two
    cannot drift:

    * multi-member: concat, stable argsort, gap where ``diff >= mz_accuracy``
      — all float64 (ref src/average_spectrum_clustering.py:56-67);
      ``tail_mode == "reference"`` drops the final gap when there are >= 2
      gaps (ref :79-87, the ``ind_list[1:-1]`` loop)
    * singleton: peaks pass through in INPUT order, each its own segment
      (ref :88-90 — no sort, no grouping)
    """
    if len(members) == 1:
        s = members[0]
        mz = s.mz.astype(np.float64, copy=False)
        inten = s.intensity.astype(np.float64, copy=False)
        return mz, inten, np.arange(mz.size, dtype=np.int32)
    mz = np.concatenate([s.mz for s in members]).astype(
        np.float64, copy=False
    )
    inten = np.concatenate([s.intensity for s in members]).astype(
        np.float64, copy=False
    )
    order = np.argsort(mz, kind="stable")
    mz = mz[order]
    inten = inten[order]
    gap = np.diff(mz) >= config.mz_accuracy
    if config.tail_mode == "reference":
        idx = np.flatnonzero(gap)
        if idx.size >= 2:
            gap[idx[-1]] = False
    seg = np.zeros(mz.size, dtype=np.int32)
    if mz.size:
        seg[1:] = np.cumsum(gap)
    return mz, inten, seg


def bin_mean_bins(
    mz: np.ndarray, config: BinMeanConfig
) -> tuple[np.ndarray, np.ndarray]:
    """K1 grid quantization, float64 — THE single implementation shared by
    the numpy oracle and every device packer (so the grids cannot drift).

    Returns ``(bins64, in_range)``:

    * ``"da"``: ``((mz - min_mz) / bin_size).astype(int64)`` — the
      reference's fixed grid (ref src/binning.py:195);
    * ``"ppm"``: ``floor(ln(mz / min_mz) / ln(1 + ppm*1e-6))`` —
      mass-proportional bins whose width is ``ppm`` of the m/z at that
      point (BASELINE configs[3] generalization; no reference analogue).

    ``in_range`` is the reference's ``[min_mz, max_mz)`` window; bins of
    out-of-range peaks are whatever the formula yields and must be masked
    by the caller.
    """
    from specpride_tpu.config import ppm_bin_index

    mzf = np.asarray(mz, dtype=np.float64)
    in_range = (mzf >= config.min_mz) & (mzf < config.max_mz)
    if config.tolerance_mode == "ppm":
        bins = ppm_bin_index(mzf, config.min_mz, config.ppm)
    else:
        bins = ((mzf - config.min_mz) / config.bin_size).astype(np.int64)
    return bins, in_range


def cosine_normalize(intensity: np.ndarray, config: CosineConfig) -> np.ndarray:
    """Intensity transform before cosine binning (BASELINE configs[3]):
    identity, sqrt, or log1p — one implementation for the oracle, the
    native kernel wrapper, and both device packers."""
    if config.normalization == "sqrt":
        return np.sqrt(np.asarray(intensity, dtype=np.float64))
    if config.normalization == "log":
        return np.log1p(np.asarray(intensity, dtype=np.float64))
    return intensity


def distinct_bins_per_row(bins: np.ndarray, sentinel: int) -> np.ndarray:
    """(B,) number of distinct non-sentinel bin values per row — the exact
    per-cluster consensus output bound, used to size the globally-compacted
    device output buffer (D2H bytes are the bottleneck on tunneled hosts)."""
    if bins.size == 0:
        return np.zeros((bins.shape[0],), dtype=np.int64)
    s = np.sort(bins, axis=1)
    first = (s[:, :1] < sentinel).astype(np.int64)[:, 0]
    changes = ((s[:, 1:] != s[:, :-1]) & (s[:, 1:] < sentinel)).sum(axis=1)
    return first + changes


def medoid_bins_packed(batch, config: MedoidConfig) -> np.ndarray:
    """(B, K) GLOBAL occupancy-grid bin indices (``floor(mz / bin_size)``,
    float64), sentinel 2**30 for padding slots.  Pairwise shared-bin counts
    are origin-independent, so no per-cluster rel-bin/span pass exists (the
    old span-derived ``grid`` was a data-dependent static jit arg — one XLA
    recompile per batch)."""
    valid = batch.member_id >= 0
    bins = (batch.mz64 / config.bin_size).astype(np.int64)
    sent = np.int64(2**30)
    return np.where(valid, np.clip(bins, 0, sent - 1), sent).astype(np.int32)


def cosine_edge_count(last_mz, space):
    """Edge count of the metric grid ``arange(-space/2, last_mz, space)``
    (numpy arange length = ceil((stop - start)/step)), float64.  Shared by
    rep-side quantization (``cosine_bins``) and the per-member pair cutoff
    (``backends.tpu_backend``) so the grid definition lives in one place."""
    n = np.ceil((np.asarray(last_mz, dtype=np.float64) + space / 2.0) / space)
    return np.where(np.isfinite(n), np.maximum(n, 0), 0).astype(np.int32)


# ---------------------------------------------------------------------------
# Reduced-precision packed encodings (--precision {f32,bf16,int8})
# ---------------------------------------------------------------------------
#
# Representation precision is a tunable quality/cost axis (arXiv:2502.10851;
# SpecHD shows low-precision packed encodings keep MS similarity quality).
# These helpers quantize the PACKED device channels at pack/ship time so the
# H2D link carries fewer bytes; the QC-cosine kernels always run at full
# precision (they are the judge side of the tolerance gate, never the
# defendant).  f32 is the byte-parity default: every encoder is an exact
# identity there.

PRECISIONS = ("f32", "bf16", "int8")

# minimum rep-vs-f32-oracle cosine the per-run gate enforces for a reduced
# run (sampled clusters; see cli._precision_gate).  The documented
# tolerance table — docs/performance.md "Memory bandwidth & precision".
# int8 stores intensity as 7-bit codes against a per-cluster scale
# (relative error <= 1/254 of the row max), bf16 keeps 8 mantissa bits
# (<= 2^-9 relative); cosine is intensity-weighted, so the bounds below
# leave an order of magnitude of slack over the worst measured drift.
PRECISION_MIN_COSINE: dict[tuple[str, str], float] = {
    ("bin-mean", "bf16"): 0.9995,
    ("bin-mean", "int8"): 0.995,
    ("gap-average", "bf16"): 0.9995,
    ("gap-average", "int8"): 0.995,
    # medoid picks an INDEX: narrowing its integer channels is exact when
    # the grid fits int16, so any divergence means a genuine near-tie —
    # gate on the two chosen members being near-identical spectra
    ("medoid", "bf16"): 0.999,
    ("medoid", "int8"): 0.999,
}


def precision_tolerance(method: str, precision: str) -> float:
    """Minimum gate cosine for (method, precision); f32 demands exact."""
    if precision == "f32":
        return 1.0
    return PRECISION_MIN_COSINE.get((method, precision), 0.995)


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


def bf16_exact(arr: np.ndarray) -> bool:
    """True when every value round-trips f32 -> bf16 -> f32 exactly.

    The pack-time "bf16 m/z where exact" probe: synthetic/coarse-grid m/z
    (and instrument exports with truncated precision) survive the cast
    bit-exactly, so the device's f32 upcast reproduces the f32 sums
    byte-identically; noisy full-precision m/z fails the probe and ships
    f32 — the m/z channel never silently degrades."""
    a = np.asarray(arr, dtype=np.float32)
    return bool(np.array_equal(a.astype(_bf16()).astype(np.float32), a))


def encode_mz(mz: np.ndarray, precision: str) -> tuple[np.ndarray, str]:
    """``(encoded, token)`` for a packed m/z channel: bf16 only when the
    round trip is exact (token "bf16"), else the f32 input unchanged
    (token "f32").  f32 precision is an identity."""
    if precision == "f32" or not bf16_exact(mz):
        return np.asarray(mz, dtype=np.float32), "f32"
    return np.asarray(mz, dtype=np.float32).astype(_bf16()), "bf16"


def encode_intensity_rows(
    intensity: np.ndarray, precision: str
) -> tuple[np.ndarray, np.ndarray | None]:
    """Encode a (B, K) packed intensity channel.  Returns
    ``(codes, scale)``:

    * f32: identity, scale None
    * bf16: bf16 cast, scale None (device upcasts; means stay f32 math)
    * int8: per-ROW symmetric 7-bit codes ``round(x / scale)`` with
      ``scale = rowmax / 127`` (f32, per cluster row).  The scale never
      ships: segment means are linear, so the HOST rescales the fetched
      means by the row scale instead (``scale`` is returned for that).
    """
    x = np.asarray(intensity, dtype=np.float32)
    if precision == "f32":
        return x, None
    if precision == "bf16":
        return x.astype(_bf16()), None
    if precision != "int8":
        raise ValueError(f"unknown precision {precision!r}")
    rowmax = np.abs(x).max(axis=-1)
    scale = np.where(rowmax > 0, rowmax / 127.0, 1.0).astype(np.float32)
    codes = np.clip(
        np.round(x / scale[..., None]), -127, 127
    ).astype(np.int8)
    return codes, scale


def encode_intensity_flat(
    intensity: np.ndarray, row_offsets: np.ndarray, precision: str
) -> tuple[np.ndarray, np.ndarray | None]:
    """Flat-layout twin of :func:`encode_intensity_rows`: ``intensity``
    is (N,) with cluster rows at ``row_offsets`` (len rows+1) slices.
    int8 scales are per ROW (cluster) — one f32 per cluster, recovered
    host-side after the device mean."""
    x = np.asarray(intensity, dtype=np.float32)
    if precision == "f32":
        return x, None
    if precision == "bf16":
        return x.astype(_bf16()), None
    if precision != "int8":
        raise ValueError(f"unknown precision {precision!r}")
    rows = row_offsets.size - 1
    if x.size:
        rowmax = np.maximum.reduceat(
            np.abs(np.append(x, np.float32(0.0))),
            np.minimum(row_offsets[:-1], x.size),
        )[:rows]
        # empty rows repeat a neighbour's start; force their max to 0
        rowmax = np.where(np.diff(row_offsets) > 0, rowmax, 0.0)
    else:
        rowmax = np.zeros(rows, dtype=np.float32)
    scale = np.where(rowmax > 0, rowmax / 127.0, 1.0).astype(np.float32)
    per_elem = np.repeat(scale, np.diff(row_offsets))
    codes = np.clip(np.round(x / per_elem), -127, 127).astype(np.int8)
    return codes, scale


def narrow_i32_to_i16(
    arr: np.ndarray, max_valid: int, sentinel: int | None = None
) -> np.ndarray | None:
    """int16 view of an int32 index channel, or None when it cannot
    narrow losslessly.  ``max_valid`` is the largest REAL value the
    channel may carry; values above it (the old int32 sentinel) map to
    ``sentinel`` (default int16 max).  Narrowing is exact — reduced
    medoid/segment channels are bit-equivalent after the device upcast —
    so the only failure mode is a grid too large for int16, and the
    caller falls back to int32 (journaled, never silent)."""
    if max_valid >= 2**15 - 1:
        return None
    a = np.asarray(arr)
    sent = np.int16(2**15 - 1 if sentinel is None else sentinel)
    return np.where(a > max_valid, sent, a).astype(np.int16)


def cosine_bins(
    mz: np.ndarray, valid: np.ndarray, config: CosineConfig
) -> tuple[np.ndarray, np.ndarray]:
    """Cosine-grid bin indices + per-spectrum edge counts, float64.

    The metric grid (ref src/benchmark.py:11-15) has edges
    ``arange(-mz_space/2, max_mz, mz_space)`` — bin index is therefore
    ``floor((mz + space/2) / space)``, *independent* of the pair's max m/z;
    only the number of edges depends on it.  Returns:

    * ``bins``: same shape as ``mz``, int32, sentinel = INT32_MAX/2 for
      invalid peaks (so they sort last);
    * ``n_edges``: per-spectrum edge count ``len(arange(-s/2, last_mz, s))``
      computed in f64.  A pair's edge count is the max of its two spectra
      (edge count is monotone in last m/z), and peaks in bins
      ``>= n_edges - 1`` fall beyond the pair's last edge and are excluded
      (ref src/benchmark.py:20-22 via scipy binned_statistic range).
    """
    space = config.mz_space
    mzf = mz.astype(np.float64)
    bins = np.floor((mzf + space / 2.0) / space).astype(np.int64)
    sentinel = np.int32(2**30)
    bins = np.where(valid, np.clip(bins, 0, sentinel - 1), sentinel)
    # the reference (and oracle) take the LAST peak's m/z, not the max
    # (``max(a.mz[-1], b.mz[-1])`` ref src/benchmark.py:20 assumes sorted
    # spectra) — reproduce exactly: value at the last valid index
    n_valid = valid.sum(axis=-1)
    last_idx = np.maximum(n_valid - 1, 0)
    last_mz = np.take_along_axis(mzf, last_idx[..., None], axis=-1)[..., 0]
    last_mz = np.where(n_valid > 0, last_mz, -np.inf)
    return bins.astype(np.int32), cosine_edge_count(last_mz, space)
