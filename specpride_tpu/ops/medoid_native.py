"""ctypes bindings for the multithreaded C++ medoid shared-bin counter
(native/medoid.cpp — exact integer pair counts; the float64 finalize stays
in ``ops.similarity.medoid_finalize``, shared with the device path so both
paths' fp semantics are identical by construction).

Loading mirrors ``ops.gap_native``: lazy, soft-failing (``available()``
False when unbuilt), reusing the one-shot ``make -C native`` bootstrap."""

from __future__ import annotations

import ctypes
import threading

import numpy as np

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    p = ctypes.POINTER
    lib.medoid_shared_run.restype = ctypes.c_int
    lib.medoid_shared_run.argtypes = [
        p(ctypes.c_double),  # mz
        p(ctypes.c_int64),  # spec_offsets
        p(ctypes.c_int64),  # cluster_spec_offsets
        p(ctypes.c_int64),  # out_offsets
        ctypes.c_int64,  # n_clusters
        ctypes.c_double,  # bin_size
        p(ctypes.c_int32),  # out_shared
        ctypes.c_int,  # n_threads
    ]
    return lib


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        from specpride_tpu.io.native import load_native

        _lib = load_native("libmedoid.so", "SPECPRIDE_MEDOID_LIB", _bind)
        _load_failed = _lib is None
        return _lib


def available() -> bool:
    """True when the C++ medoid library is built and loadable."""
    return _load() is not None


def shared_bin_counts(
    mz: np.ndarray,  # (P,) f64, spectra contiguous, clusters contiguous
    spec_offsets: np.ndarray,  # (S + 1,) i64 peak extents per spectrum
    cluster_spec_offsets: np.ndarray,  # (C + 1,) i64 spectrum extents/cluster
    bin_size: float,
    n_threads: int = 0,  # 0 = hardware concurrency
) -> tuple[np.ndarray, np.ndarray]:
    """Flat per-cluster (M, M) shared unique-bin count matrices.

    Returns ``(shared_flat, out_offsets)``: cluster c's matrix is
    ``shared_flat[out_offsets[c] : out_offsets[c + 1]].reshape(M, M)``.
    Raises ``RuntimeError`` when the library is unavailable (callers
    guard with ``available()``)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native medoid not built (make -C native)")
    mz = np.ascontiguousarray(mz, dtype=np.float64)
    spec_offsets = np.ascontiguousarray(spec_offsets, dtype=np.int64)
    cluster_spec_offsets = np.ascontiguousarray(
        cluster_spec_offsets, dtype=np.int64
    )
    c = cluster_spec_offsets.size - 1
    m_per = np.diff(cluster_spec_offsets)
    out_offsets = np.zeros(c + 1, dtype=np.int64)
    np.cumsum(m_per * m_per, out=out_offsets[1:])
    out = np.zeros(int(out_offsets[-1]), dtype=np.int32)
    dp = ctypes.POINTER(ctypes.c_double)
    ip = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    rc = lib.medoid_shared_run(
        mz.ctypes.data_as(dp),
        spec_offsets.ctypes.data_as(ip),
        cluster_spec_offsets.ctypes.data_as(ip),
        out_offsets.ctypes.data_as(ip),
        c,
        float(bin_size),
        out.ctypes.data_as(i32p),
        int(n_threads),
    )
    if rc != 0:
        raise RuntimeError(f"native medoid failed (rc={rc})")
    return out, out_offsets


def finalize_indices(
    shared_flat: np.ndarray,  # flat per-cluster (M, M) count matrices
    out_offsets: np.ndarray,  # (C + 1,) i64 extents into shared_flat
    n_peaks: np.ndarray,  # (S,) i64 raw peak counts, cluster-contiguous
    cluster_spec_offsets: np.ndarray,  # (C + 1,) i64 spectrum extents/cluster
) -> np.ndarray:
    """Winning member index per cluster from ``shared_bin_counts`` output.

    Identical float64 math to the device path (``ops.similarity
    .medoid_finalize``), grouped by member count: a single globally-padded
    (B, Mmax, Mmax) batch would inflate memory quadratically for every
    cluster off one big outlier — equal-M groups stack with ZERO padding.
    Lives here so both halves of the native medoid protocol (counts +
    finalize) stay in one module; the import is lazy because
    ``ops.similarity`` pulls in jax and this module's count path is
    jax-free."""
    from specpride_tpu.ops.similarity import medoid_finalize

    cso = cluster_spec_offsets
    m_per = np.diff(cso)
    b = cso.size - 1
    indices = np.zeros(b, dtype=np.int64)
    for m in np.unique(m_per):
        sel = np.flatnonzero(m_per == m)
        g = sel.size
        take = out_offsets[sel][:, None] + np.arange(m * m)
        shared = shared_flat[take].reshape(g, m, m).astype(np.int64)
        counts = n_peaks[cso[sel][:, None] + np.arange(m)]
        indices[sel] = medoid_finalize(
            shared,
            counts,
            np.ones((g, m), dtype=bool),
            np.full(g, m, dtype=np.int64),
        )
    return indices
