"""Pallas TPU kernels (the hand-scheduled alternatives to the XLA
formulations in ``ops.segments``).

One kernel lives here: ``seg_scan_pallas``, a single-pass segmented
inclusive scan over sorted run keys — the core primitive of the flat
bin-mean consensus (K1).  The XLA formulation (``segments.seg_scan``)
needs log2(lcap) full-array shift/select passes and a packer-guaranteed
bound on run length; the Pallas version streams blocks through VMEM once,
carrying the open run's partial sums across the sequential grid in SMEM —
exact for ANY run length, one HBM read + one write per element.

Measured A/B on the 2000-cluster bench workload (v5e, 4M peaks, 3 value
channels) lives in ``BENCH_METHODS.json`` under ``pallas_ab``; the driver
(``backends.tpu_backend``) keeps the XLA path as the default because the
end-to-end flat bin-mean is device->host-transfer-bound, not scan-bound —
the A/B exists to keep the claim honest either way (VERDICT r3 ask #4).

Import is lazy and soft: ``available()`` is False off-TPU (tests run the
kernel in interpreter mode explicitly).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BLK_ROWS = 8  # sublane dim of one grid step's block (TPU: multiple of 8)
BLK_LANES = 2048  # lane dim (TPU: multiple of 128)
BLK = BLK_ROWS * BLK_LANES  # elements per grid step


def _seg_scan_block_kernel(
    key_ref, w_ref, x_ref, y_ref,  # inputs (BLK_ROWS, BLK_LANES)
    ow_ref, ox_ref, oy_ref,  # outputs (BLK_ROWS, BLK_LANES)
    carry_key, carry_sums,  # SMEM scratch: (1,) i32, (3,) f32
):
    """One grid step: within-block segmented scan + cross-block carry.

    The (BLK_ROWS, BLK_LANES) tile is one contiguous row-major span of
    the flat axis.  Mosaic has no 1-D reshape/cumsum lowerings, so the
    scan is lane-axis Hillis-Steele per row followed by a statically
    unrolled row chain (8 rows), and open-run prefixes are detected by
    key equality (keys are sorted: a row's leading run is exactly
    ``key == key[row, 0]``)."""
    i = pl.program_id(0)

    key = key_ref[:]
    vs = [w_ref[:], x_ref[:], y_ref[:]]

    # per-row lane scan: starts at lane 0 and at key changes.  Shifts use
    # pltpu.roll + iota masks with INT32 flags — Mosaic has no lowering
    # for concatenating or rolling bool vectors.
    col = jax.lax.broadcasted_iota(
        jnp.int32, (BLK_ROWS, BLK_LANES), 1
    )
    prev = jnp.where(col >= 1, pltpu.roll(key, 1, 1), key - 1)
    f = jnp.where(
        (col == 0) | (key != prev), jnp.int32(1), jnp.int32(0)
    )
    d = 1
    while d < BLK_LANES:
        fs = jnp.where(col >= d, pltpu.roll(f, d, 1), jnp.int32(1))
        vs = [
            jnp.where(
                f == 1, v,
                v + jnp.where(col >= d, pltpu.roll(v, d, 1), 0.0),
            )
            for v in vs
        ]
        f = f | fs
        d *= 2

    # chain rows (and the previous block into row 0) — static unroll
    rows = [[v[r : r + 1, :] for r in range(BLK_ROWS)] for v in vs]
    krows = [key[r : r + 1, :] for r in range(BLK_ROWS)]
    cont0 = (
        (krows[0] == krows[0][0, 0])
        & (krows[0][0, 0] == carry_key[0])
        & (i > 0)
    )
    carries = [carry_sums[0], carry_sums[1], carry_sums[2]]
    for c in range(3):
        rows[c][0] = rows[c][0] + jnp.where(cont0, carries[c], 0.0)
    for r in range(1, BLK_ROWS):
        ck = krows[r - 1][0, BLK_LANES - 1]
        cont = (krows[r] == krows[r][0, 0]) & (krows[r][0, 0] == ck)
        for c in range(3):
            rows[c][r] = rows[c][r] + jnp.where(
                cont, rows[c][r - 1][0, BLK_LANES - 1], 0.0
            )

    for ref, c in ((ow_ref, 0), (ox_ref, 1), (oy_ref, 2)):
        ref[:] = jnp.concatenate(rows[c], axis=0)

    carry_key[0] = key[BLK_ROWS - 1, BLK_LANES - 1]
    for c in range(3):
        carry_sums[c] = rows[c][BLK_ROWS - 1][0, BLK_LANES - 1]


def seg_scan_pallas(
    keys: jax.Array,  # (N,) i32 sorted run keys; N a multiple of BLK
    w: jax.Array,  # (N,) f32
    x: jax.Array,  # (N,) f32
    y: jax.Array,  # (N,) f32
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Segmented inclusive prefix sums of (w, x, y) within runs of equal
    ``keys`` — the Pallas single-pass equivalent of
    ``segments.seg_scan(run_starts(keys), (w, x, y), lcap)`` with no run
    length bound."""
    n = keys.shape[0]
    assert n % BLK == 0, n
    nb = n // BLK
    rows = nb * BLK_ROWS
    spec = pl.BlockSpec((BLK_ROWS, BLK_LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        _seg_scan_block_kernel,
        grid=(nb,),
        in_specs=[spec] * 4,
        out_specs=[spec] * 3,
        out_shape=[
            jax.ShapeDtypeStruct((rows, BLK_LANES), jnp.float32)
            for _ in range(3)
        ],
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((3,), jnp.float32),
        ],
        interpret=interpret,
    )(
        keys.reshape(rows, BLK_LANES),
        w.reshape(rows, BLK_LANES),
        x.reshape(rows, BLK_LANES),
        y.reshape(rows, BLK_LANES),
    )
    return tuple(o.reshape(n) for o in out)


def available() -> bool:
    """True when Pallas TPU lowering is usable on the default backend."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # backend init failure — no device path at all
        return False


try:  # pallas imports kept at module scope for the kernel body
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - pallas ships with jax on TPU
    pl = None
    pltpu = None
