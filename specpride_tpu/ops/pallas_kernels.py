"""Pallas TPU kernels (the hand-scheduled alternatives to the XLA
formulations in ``ops.segments``).

Two kernels live here, sharing one block-scan core:

* ``seg_scan_pallas`` — a single-pass segmented inclusive scan over
  sorted run keys (3 fixed value channels), the original A/B subject
  against ``segments.seg_scan``.
* ``seg_mean_pallas`` — the FUSED segment-mean kernel: run detection,
  the valid-mask weighting, segmented sums and the per-run mean all in
  one VMEM-resident pass (1 or 2 value channels + a count channel).
  This is the Pallas alternative the routing table
  (``warmstart.routing``) can select for the flat bin-mean intensity
  kernel and the bucketized gap-average kernel — the XLA formulation
  needs log2(lcap) full-array shift/select passes and a
  packer-guaranteed bound on run length; the Pallas version streams
  blocks through VMEM once, carrying the open run's partial sums across
  the sequential grid in SMEM — exact for ANY run length, one HBM read
  + one write per element, and the division to means happens in the
  same pass so no separate mean kernel ever materialises.

Measured A/B on the bench workload lives in the ``pallas_ab`` section
of the BENCH reports; promotion to the default path happens through a
bench-derived routing override, never by edit
(``docs/performance.md#warm-start``).

Import is lazy and soft: ``has_pallas()`` is False off-TPU (tests run
the kernels in interpreter mode explicitly).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BLK_ROWS = 8  # sublane dim of one grid step's block (TPU: multiple of 8)
BLK_LANES = 2048  # lane dim (TPU: multiple of 128)
BLK = BLK_ROWS * BLK_LANES  # elements per grid step


def _block_scan_chain(i, key, vs, carry_key, carry_sums):
    """One grid step's segmented inclusive scan over ``len(vs)`` channels:
    within-block scan + cross-block carry.

    The (BLK_ROWS, BLK_LANES) tile is one contiguous row-major span of
    the flat axis.  Mosaic has no 1-D reshape/cumsum lowerings, so the
    scan is lane-axis Hillis-Steele per row followed by a statically
    unrolled row chain (8 rows), and open-run prefixes are detected by
    key equality (keys are sorted: a row's leading run is exactly
    ``key == key[row, 0]``).  Returns the chained full-tile prefix per
    channel and updates the SMEM carries (``carry_key`` (1,) i32,
    ``carry_sums`` (len(vs),) f32) for the next grid step."""
    nv = len(vs)

    # per-row lane scan: starts at lane 0 and at key changes.  Shifts use
    # pltpu.roll + iota masks with INT32 flags — Mosaic has no lowering
    # for concatenating or rolling bool vectors.
    col = jax.lax.broadcasted_iota(
        jnp.int32, (BLK_ROWS, BLK_LANES), 1
    )
    prev = jnp.where(col >= 1, pltpu.roll(key, 1, 1), key - 1)
    f = jnp.where(
        (col == 0) | (key != prev), jnp.int32(1), jnp.int32(0)
    )
    d = 1
    while d < BLK_LANES:
        fs = jnp.where(col >= d, pltpu.roll(f, d, 1), jnp.int32(1))
        vs = [
            jnp.where(
                f == 1, v,
                v + jnp.where(col >= d, pltpu.roll(v, d, 1), 0.0),
            )
            for v in vs
        ]
        f = f | fs
        d *= 2

    # chain rows (and the previous block into row 0) — static unroll
    rows = [[v[r : r + 1, :] for r in range(BLK_ROWS)] for v in vs]
    krows = [key[r : r + 1, :] for r in range(BLK_ROWS)]
    cont0 = (
        (krows[0] == krows[0][0, 0])
        & (krows[0][0, 0] == carry_key[0])
        & (i > 0)
    )
    for c in range(nv):
        rows[c][0] = rows[c][0] + jnp.where(cont0, carry_sums[c], 0.0)
    for r in range(1, BLK_ROWS):
        ck = krows[r - 1][0, BLK_LANES - 1]
        cont = (krows[r] == krows[r][0, 0]) & (krows[r][0, 0] == ck)
        for c in range(nv):
            rows[c][r] = rows[c][r] + jnp.where(
                cont, rows[c][r - 1][0, BLK_LANES - 1], 0.0
            )

    out = [jnp.concatenate(rows[c], axis=0) for c in range(nv)]
    carry_key[0] = key[BLK_ROWS - 1, BLK_LANES - 1]
    for c in range(nv):
        carry_sums[c] = rows[c][BLK_ROWS - 1][0, BLK_LANES - 1]
    return out


def _seg_scan_block_kernel(
    key_ref, w_ref, x_ref, y_ref,  # inputs (BLK_ROWS, BLK_LANES)
    ow_ref, ox_ref, oy_ref,  # outputs (BLK_ROWS, BLK_LANES)
    carry_key, carry_sums,  # SMEM scratch: (1,) i32, (3,) f32
):
    """Plain 3-channel segmented inclusive scan (``seg_scan_pallas``)."""
    i = pl.program_id(0)
    outs = _block_scan_chain(
        i, key_ref[:], [w_ref[:], x_ref[:], y_ref[:]],
        carry_key, carry_sums,
    )
    for ref, o in zip((ow_ref, ox_ref, oy_ref), outs):
        ref[:] = o


@functools.lru_cache(maxsize=None)
def _seg_mean_block_kernel(nv: int):
    """Fused segment-mean kernel body for ``nv`` value channels: the
    same block scan over (w, v_0 * w, ..) plus the in-pass division to
    means.  ``w`` is the 0/1 valid mask — invalid (padding/sentinel)
    elements contribute nothing and read back count 0 / mean 0."""

    def kernel(*refs):
        key_ref, w_ref = refs[0], refs[1]
        val_refs = refs[2 : 2 + nv]
        out_refs = refs[2 + nv : 3 + 2 * nv]
        carry_key, carry_sums = refs[3 + 2 * nv], refs[4 + 2 * nv]
        i = pl.program_id(0)
        w = w_ref[:]
        sums = _block_scan_chain(
            i, key_ref[:], [w] + [r[:] * w for r in val_refs],
            carry_key, carry_sums,
        )
        cnt = sums[0]
        safe = jnp.maximum(cnt, 1.0)
        out_refs[0][:] = cnt
        for c in range(nv):
            out_refs[1 + c][:] = sums[1 + c] / safe

    return kernel


def seg_scan_pallas(
    keys: jax.Array,  # (N,) i32 sorted run keys; N a multiple of BLK
    w: jax.Array,  # (N,) f32
    x: jax.Array,  # (N,) f32
    y: jax.Array,  # (N,) f32
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Segmented inclusive prefix sums of (w, x, y) within runs of equal
    ``keys`` — the Pallas single-pass equivalent of
    ``segments.seg_scan(run_starts(keys), (w, x, y), lcap)`` with no run
    length bound."""
    n = keys.shape[0]
    assert n % BLK == 0, n
    nb = n // BLK
    rows = nb * BLK_ROWS
    spec = pl.BlockSpec((BLK_ROWS, BLK_LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        _seg_scan_block_kernel,
        grid=(nb,),
        in_specs=[spec] * 4,
        out_specs=[spec] * 3,
        out_shape=[
            jax.ShapeDtypeStruct((rows, BLK_LANES), jnp.float32)
            for _ in range(3)
        ],
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((3,), jnp.float32),
        ],
        interpret=interpret,
    )(
        keys.reshape(rows, BLK_LANES),
        w.reshape(rows, BLK_LANES),
        x.reshape(rows, BLK_LANES),
        y.reshape(rows, BLK_LANES),
    )
    return tuple(o.reshape(n) for o in out)


def seg_mean_pallas(
    keys: jax.Array,  # (N,) i32 sorted run keys; N a multiple of BLK
    w: jax.Array,  # (N,) f32 0/1 valid mask (the weight channel)
    *values: jax.Array,  # 1 or 2 (N,) f32 value channels
    interpret: bool = False,
) -> tuple[jax.Array, ...]:
    """Fused single-pass segment means: ``(count, mean_0[, mean_1])``
    per element, where ``count`` is the within-run inclusive prefix of
    ``w`` and ``mean_c = seg_prefix(values[c] * w) / max(count, 1)``.

    At a run's LAST element the prefix covers the whole run, so
    gathering the outputs at ``segments.run_end_positions`` yields the
    per-run means directly — callers replace the log2(lcap)-step XLA
    shift/select chain AND the separate division with this one pass.
    Invalid elements (``w == 0``: padding tails, sentinel slots) add
    nothing and report count 0 / mean 0."""
    nv = len(values)
    assert nv in (1, 2), nv
    n = keys.shape[0]
    assert n % BLK == 0, n
    nb = n // BLK
    rows = nb * BLK_ROWS
    spec = pl.BlockSpec((BLK_ROWS, BLK_LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        _seg_mean_block_kernel(nv),
        grid=(nb,),
        in_specs=[spec] * (2 + nv),
        out_specs=[spec] * (1 + nv),
        out_shape=[
            jax.ShapeDtypeStruct((rows, BLK_LANES), jnp.float32)
            for _ in range(1 + nv)
        ],
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1 + nv,), jnp.float32),
        ],
        interpret=interpret,
    )(
        keys.reshape(rows, BLK_LANES),
        w.reshape(rows, BLK_LANES),
        *[v.reshape(rows, BLK_LANES) for v in values],
    )
    return tuple(o.reshape(n) for o in out)


def pad_to_block(n: int) -> int:
    """Smallest multiple of ``BLK`` >= n (static shape helper for the
    jit-level wrappers that feed the flat kernels)."""
    return -(-max(n, 1) // BLK) * BLK


def has_pallas() -> bool:
    """True when Pallas TPU lowering is usable on the default backend
    (tests run the kernels in interpreter mode explicitly instead)."""
    if pl is None:
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # backend init failure — no device path at all
        return False


# historical name, kept for external callers
available = has_pallas


try:  # pallas imports kept at module scope for the kernel body
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - pallas ships with jax on TPU
    pl = None
    pltpu = None
