"""K3: gap-clustered average consensus device kernel (JAX/XLA).

TPU-native replacement for ref src/average_spectrum_clustering.py:26-103
(``average_spectrum``): the reference concatenates member peaks, sorts,
splits at m/z gaps, then walks the gap list in a sequential Python loop with
cumsum prefix sums.  Here the whole batch is one jitted program — the
sequential group walk becomes ``segment_sum`` over pre-computed segment ids,
which XLA executes as parallel segmented reductions.

Float64 split of responsibilities (same pattern as K1, see
``ops.quantize``): gap detection compares m/z differences against
``mz_accuracy`` (0.01 Da) — at m/z ~1700 the float32 ulp (~1.2e-4) is an
order of magnitude wider than realistic jitter around that threshold, so
deciding gaps in f32 on device silently regroups peaks vs the reference's
float64 ``np.diff`` (ref :62-67).  The host therefore sorts each cluster's
concatenated peaks and derives gap/segment ids in float64 at pack time
(``data.packed.pack_bucketize_gap``), including the reference's
final-gap-merge (``tail_mode="reference"``, ref :79-87) and the integer
quorum threshold; the device receives sorted peaks + int32 segment ids and
does only the heavy parallel work.

Semantics reproduced (see the numpy oracle
``backends.numpy_backend.gap_average_consensus`` for the cited mapping):

* group mean m/z = group_sum / group_size; group intensity =
  group_sum / n_members (ref :76-77,81-82,86-87)
* quorum: group_size >= min_fraction * n_members (ref :74,80,85) — shipped
  as a per-cluster integer threshold (exact for integer group sizes)
* dynamic-range floor max/dyn_range applied after grouping (ref :95-98)
* singleton clusters pass through ungrouped in INPUT order (ref :88-90) —
  the host assigns each peak its own segment without sorting

Remaining documented divergence: group sums/means run in float32 on device
(vs float64 in the oracle).  The *segmentation* (which peaks group together)
is exact — it is decided host-side in f64 — but downstream of it the
dynamic-range keep decision (``group_int >= kept_max / dyn_range``) compares
f32 intensities, so a group whose f64 intensity sits within one f32 ulp of
the floor can be kept/dropped differently from the oracle.  Unlike the gap
threshold (a fixed grid that real data clusters around), this boundary is
data-dependent and measure-zero for measured intensities.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from specpride_tpu.config import GapAverageConfig
from specpride_tpu.ops.jit_util import jit_pair


def _gap_average_segment_stats(
    mz: jax.Array,  # (B, K) f32, rows sorted ascending (singletons: input)
    intensity: jax.Array,  # (B, K) f32
    seg: jax.Array,  # (B, K) i32 host-computed segment ids, non-decreasing
    n_valid: jax.Array,  # (B,) i32 — packed peaks are contiguous
    quorum: jax.Array,  # (B,) i32 — host-f64 ceil(min_fraction * n_members)
    n_members: jax.Array,  # (B,) i32
    config: GapAverageConfig,
    impl: str = "scan",  # "scan" | "pallas" | "pallas_interpret"
):
    """Per-cluster per-group stats (mz mean, intensity, keep mask) at
    GROUP-END positions — the (B, K) core of ``gap_average_compact``.

    Row-local segmented scans (``ops.segments.seg_scan``) replace the
    vmapped ``segment_sum`` — TPU scatter-adds with duplicate indices
    serialize — and stay shard-local under a cluster-axis mesh.

    ``impl="pallas"`` swaps the log2-step scan chain for the fused
    single-pass Pallas segment-mean kernel over the row-major flattened
    batch (rows become disjoint key ranges via a (row, seg) composite,
    so the 1-D kernel respects row boundaries by construction); the
    routing table in the tpu backend picks per platform."""
    from specpride_tpu.ops import segments as sg

    # reduced-precision packed inputs (--precision): upcast at entry —
    # exact for bf16-exact m/z, int8 intensity codes (host rescales the
    # fetched means by the per-cluster scale; the dyn-range keep compare
    # is scale-invariant within a row), and int16-narrowed segment ids
    mz = mz.astype(jnp.float32)
    intensity = intensity.astype(jnp.float32)
    seg = seg.astype(jnp.int32)

    b, k = mz.shape
    valid = jnp.arange(k)[None, :] < n_valid[:, None]
    w = jnp.where(valid, 1.0, 0.0)

    # padding slots carry seg id 0 (the packer zero-fills, see
    # data/packed.py pack_bucketize_gap), which would otherwise alias the
    # row's FIRST group; remap the tail to its own out-of-range run id
    key = jnp.where(valid, seg, jnp.int32(k + 1))
    starts = sg.run_starts2d(key)
    nm = n_members.astype(jnp.float32)[:, None]
    if impl == "scan":
        sizes, mz_sums, int_sums = sg.seg_scan(
            starts, (w, mz * w, intensity * w), k
        )
        group_mz = mz_sums / jnp.maximum(sizes, 1.0)
        group_int = int_sums / jnp.maximum(nm, 1.0)
    else:
        from specpride_tpu.ops import pallas_kernels as pk

        row = jax.lax.broadcasted_iota(jnp.int32, (b, k), 0)
        ck = (row * jnp.int32(k + 2) + key).reshape(b * k)
        n = b * k
        pad = pk.pad_to_block(n) - n
        cnt, mean_mz, mean_int = pk.seg_mean_pallas(
            # -1 never collides with a real composite (all >= 0), so the
            # pad tail is its own zero-weight run
            jnp.pad(ck, (0, pad), constant_values=-1),
            jnp.pad(w.reshape(n), (0, pad)),
            jnp.pad(mz.reshape(n), (0, pad)),
            jnp.pad(intensity.reshape(n), (0, pad)),
            interpret=(impl == "pallas_interpret"),
        )
        sizes = cnt[:n].reshape(b, k)
        group_mz = mean_mz[:n].reshape(b, k)
        # the kernel fuses the by-count mean; gap intensity divides by
        # n_members instead (ref :76-77), so scale back through sizes
        group_int = mean_int[:n].reshape(b, k) * sizes / jnp.maximum(
            nm, 1.0
        )
    is_end = sg.run_ends2d(starts)

    keep = (
        is_end
        & valid
        & (sizes > 0)
        & (sizes >= quorum.astype(jnp.float32)[:, None])
    )
    kept_max = jnp.max(
        jnp.where(keep, group_int, -jnp.inf), axis=1, keepdims=True
    )
    floor = kept_max / config.dyn_range
    keep &= group_int >= floor
    return group_mz, group_int, keep


def _gap_average_compact(
    mz: jax.Array,  # (B, K) f32
    intensity: jax.Array,  # (B, K) f32
    seg: jax.Array,  # (B, K) i32
    n_valid: jax.Array,  # (B,) i32
    quorum: jax.Array,  # (B,) i32
    n_members: jax.Array,  # (B,) i32
    config: GapAverageConfig,
    total_cap: int,
    impl: str = "scan",  # segmented-reduction core, see the stats fn
):
    """Globally-compacted gap-average: one fused 1-D output
    ``[flat_mz (total_cap) | flat_intensity (total_cap) | n_out (B)]``.

    ``total_cap`` must be >= the batch's total group count — the host knows
    each cluster's exact group count (``GapPackedBatch.n_groups``, a by-
    product of the f64 gap precompute), so unlike the earlier f32 kernel
    there is no data-dependent overflow and no redispatch path.  Outputs are
    row-major: cluster order preserved, ascending m/z within a cluster
    (input order for singletons, matching ref :88-90)."""
    b, k = mz.shape
    group_mz, group_int, keep = _gap_average_segment_stats(
        mz, intensity, seg, n_valid, quorum, n_members, config, impl
    )

    n_out = jnp.sum(keep, axis=1).astype(jnp.float32)
    flat_keep = keep.reshape(b * k)
    (idx,) = jnp.nonzero(flat_keep, size=total_cap, fill_value=b * k)
    ok = idx < b * k
    flat_mz = jnp.where(
        ok, group_mz.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    flat_int = jnp.where(
        ok,
        group_int.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0),
        0.0,
    )
    return jnp.concatenate([flat_mz, flat_int, n_out])


gap_average_compact, gap_average_compact_donated = jit_pair(
    _gap_average_compact,
    static_argnames=("config", "total_cap", "impl"),
    donate_argnums=(0, 1, 2, 3, 4, 5),
)
