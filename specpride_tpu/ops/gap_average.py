"""K3: gap-clustered average consensus device kernel (JAX/XLA).

TPU-native replacement for ref src/average_spectrum_clustering.py:26-103
(``average_spectrum``): the reference concatenates member peaks, sorts,
splits at m/z gaps, then walks the gap list in a sequential Python loop with
cumsum prefix sums.  Here the whole batch is one jitted program — the
sequential group walk becomes ``segment_sum`` over segment ids derived from a
cumulative gap count, which XLA executes as parallel segmented reductions.

Semantics reproduced (see the numpy oracle
``backends.numpy_backend.gap_average_consensus`` for the cited mapping):

* gap where ``diff(sorted mz) >= mz_accuracy`` (ref :62-67)
* ``tail_mode="reference"``: with >= 2 gaps the final gap is ignored, merging
  the last two groups (the ``ind_list[1:-1]`` loop, ref :79-87)
* group mean m/z = group_sum / group_size; group intensity =
  group_sum / n_members (ref :76-77,81-82,86-87)
* quorum: group_size >= min_fraction * n_members (ref :74,80,85)
* dynamic-range floor max/dyn_range applied after grouping (ref :95-98)
* singleton clusters pass through ungrouped (ref :88-90) — realised by
  forcing every inter-peak boundary to be a gap when n_members == 1, which
  makes each peak its own group (quorum 1 >= 0.5 always passes)

Divergence (documented): device output is in ascending-m/z order; for
singleton clusters with unsorted input peaks the reference preserves input
order.  Both paths emit identical multisets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import GapAverageConfig


def _gap_average_packed_cluster(
    mz: jax.Array,  # (K,) f32
    intensity: jax.Array,  # (K,) f32
    n_valid: jax.Array,  # () i32 — packed peaks are contiguous
    n_members: jax.Array,  # () i32
    config: GapAverageConfig,
    out_size: int,
):
    """Packed-layout gap average: identical math to ``_gap_average_cluster``
    but over K packed peaks (the reference concatenates members anyway, ref
    src/average_spectrum_clustering.py:56-57 — the packed layout IS that
    concatenation, so no flatten step, no (member, peak) padding, and no
    member channel: validity is just position < n_valid)."""
    k = mz.shape[0]
    valid = jnp.arange(k) < n_valid
    mz_flat = jnp.where(valid, mz, jnp.inf)
    int_flat = jnp.where(valid, intensity, 0.0)

    order = jnp.argsort(mz_flat, stable=True)
    mz_s = mz_flat[order]
    int_s = int_flat[order]

    pos = jnp.arange(k - 1, dtype=jnp.int32)
    in_valid = pos + 1 < n_valid
    gap = (mz_s[1:] - mz_s[:-1] >= config.mz_accuracy) & in_valid
    gap = jnp.where(n_members == 1, in_valid, gap)

    if config.tail_mode == "reference":
        n_gaps = jnp.sum(gap)
        last_gap = jnp.max(jnp.where(gap, pos, -1))
        drop_last = (n_gaps >= 2) & (n_members > 1)
        gap = gap & ~(drop_last & (pos == last_gap))

    seg = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(gap).astype(jnp.int32)]
    )
    in_range = jnp.arange(k) < n_valid
    ones = jnp.where(in_range, 1.0, 0.0)
    sizes = jax.ops.segment_sum(ones, seg, num_segments=k, indices_are_sorted=True)
    mz_sums = jax.ops.segment_sum(
        jnp.where(in_range, mz_s, 0.0), seg, num_segments=k, indices_are_sorted=True
    )
    int_sums = jax.ops.segment_sum(
        int_s, seg, num_segments=k, indices_are_sorted=True
    )

    nm = n_members.astype(jnp.float32)
    group_mz = mz_sums / jnp.maximum(sizes, 1.0)
    group_int = int_sums / jnp.maximum(nm, 1.0)

    keep = (sizes > 0) & (sizes >= config.min_fraction * nm)
    kept_max = jnp.max(jnp.where(keep, group_int, -jnp.inf))
    floor = kept_max / config.dyn_range
    keep &= group_int >= floor

    (idx,) = jnp.nonzero(keep, size=out_size, fill_value=k)
    valid_out = idx < k
    out_mz = jnp.where(valid_out, group_mz.at[idx].get(mode="fill", fill_value=0.0), 0.0)
    out_int = jnp.where(
        valid_out, group_int.at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    # n_out reports the TRUE group count; if it exceeds out_size the caller
    # must redispatch with a bigger buffer (the first out_size groups are
    # valid either way — nonzero fills in ascending index order)
    n_out = jnp.sum(keep).astype(jnp.float32)
    return jnp.concatenate([out_mz, out_int, n_out[None]])


@functools.partial(jax.jit, static_argnames=("config", "out_size"))
def gap_average_packed(
    mz: jax.Array,  # (B, K) f32
    intensity: jax.Array,  # (B, K) f32
    n_valid: jax.Array,  # (B,) i32
    n_members: jax.Array,  # (B,) i32
    config: GapAverageConfig,
    out_size: int | None = None,
):
    """vmapped packed gap-average.  Returns (B, 2*out_size + 1) fused rows
    [mz | intensity | n_out] — one device→host transfer per batch.  n_out
    may exceed out_size (overflow): caller redispatches with out_size=K."""
    if out_size is None:
        out_size = mz.shape[1]
    return jax.vmap(
        lambda a, b, c, d: _gap_average_packed_cluster(
            a, b, c, d, config, out_size
        )
    )(mz, intensity, n_valid, n_members)
