"""K3: gap-clustered average consensus device kernel (JAX/XLA).

TPU-native replacement for ref src/average_spectrum_clustering.py:26-103
(``average_spectrum``): the reference concatenates member peaks, sorts,
splits at m/z gaps, then walks the gap list in a sequential Python loop with
cumsum prefix sums.  Here the whole batch is one jitted program — the
sequential group walk becomes ``segment_sum`` over segment ids derived from a
cumulative gap count, which XLA executes as parallel segmented reductions.

Semantics reproduced (see the numpy oracle
``backends.numpy_backend.gap_average_consensus`` for the cited mapping):

* gap where ``diff(sorted mz) >= mz_accuracy`` (ref :62-67)
* ``tail_mode="reference"``: with >= 2 gaps the final gap is ignored, merging
  the last two groups (the ``ind_list[1:-1]`` loop, ref :79-87)
* group mean m/z = group_sum / group_size; group intensity =
  group_sum / n_members (ref :76-77,81-82,86-87)
* quorum: group_size >= min_fraction * n_members (ref :74,80,85)
* dynamic-range floor max/dyn_range applied after grouping (ref :95-98)
* singleton clusters pass through ungrouped (ref :88-90) — realised by
  forcing every inter-peak boundary to be a gap when n_members == 1, which
  makes each peak its own group (quorum 1 >= 0.5 always passes)

Divergence (documented): device output is in ascending-m/z order; for
singleton clusters with unsorted input peaks the reference preserves input
order.  Both paths emit identical multisets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import GapAverageConfig


def _gap_average_cluster(
    mz: jax.Array,  # (M, P) f32
    intensity: jax.Array,  # (M, P) f32
    peak_mask: jax.Array,  # (M, P) bool
    member_mask: jax.Array,  # (M,) bool
    n_members: jax.Array,  # () i32
    config: GapAverageConfig,
):
    m, p = mz.shape
    mp = m * p
    valid = (peak_mask & member_mask[:, None]).reshape(mp)
    mz_flat = jnp.where(valid, mz.reshape(mp), jnp.inf)
    int_flat = jnp.where(valid, intensity.reshape(mp), 0.0)

    order = jnp.argsort(mz_flat, stable=True)
    mz_s = mz_flat[order]
    int_s = int_flat[order]
    n_valid = jnp.sum(valid).astype(jnp.int32)

    pos = jnp.arange(mp - 1, dtype=jnp.int32)
    in_valid = pos + 1 < n_valid  # boundary between two valid peaks
    gap = (mz_s[1:] - mz_s[:-1] >= config.mz_accuracy) & in_valid
    # singleton passthrough: every peak its own group (ref :88-90)
    gap = jnp.where(n_members == 1, in_valid, gap)

    if config.tail_mode == "reference":
        n_gaps = jnp.sum(gap)
        last_gap = jnp.max(jnp.where(gap, pos, -1))
        drop_last = (n_gaps >= 2) & (n_members > 1)
        gap = gap & ~(drop_last & (pos == last_gap))

    seg = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(gap).astype(jnp.int32)]
    )
    in_range = jnp.arange(mp) < n_valid
    ones = jnp.where(in_range, 1.0, 0.0)
    sizes = jax.ops.segment_sum(ones, seg, num_segments=mp, indices_are_sorted=True)
    mz_sums = jax.ops.segment_sum(
        jnp.where(in_range, mz_s, 0.0), seg, num_segments=mp, indices_are_sorted=True
    )
    int_sums = jax.ops.segment_sum(
        int_s, seg, num_segments=mp, indices_are_sorted=True
    )

    nm = n_members.astype(jnp.float32)
    group_mz = mz_sums / jnp.maximum(sizes, 1.0)
    group_int = int_sums / jnp.maximum(nm, 1.0)

    keep = (sizes > 0) & (sizes >= config.min_fraction * nm)
    kept_max = jnp.max(jnp.where(keep, group_int, -jnp.inf))
    floor = kept_max / config.dyn_range
    keep &= group_int >= floor

    (idx,) = jnp.nonzero(keep, size=mp, fill_value=mp)
    valid_out = idx < mp
    out_mz = jnp.where(valid_out, group_mz.at[idx].get(mode="fill", fill_value=0.0), 0.0)
    out_int = jnp.where(
        valid_out, group_int.at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    n_out = jnp.sum(keep).astype(jnp.int32)
    return out_mz, out_int, n_out


@functools.partial(jax.jit, static_argnames=("config",))
def gap_average_batch(
    mz: jax.Array,  # (B, M, P) f32
    intensity: jax.Array,  # (B, M, P) f32
    peak_mask: jax.Array,  # (B, M, P) bool
    member_mask: jax.Array,  # (B, M) bool
    n_members: jax.Array,  # (B,) i32
    config: GapAverageConfig,
):
    """vmapped gap-average consensus over a padded cluster batch.

    Returns (out_mz (B, M*P), out_intensity (B, M*P), n_out (B,)); valid
    output peaks are the first n_out[b] entries of row b in ascending m/z.
    Precursor m/z / charge / RT estimators are host-side
    (``backends.numpy_backend.PEPMASS_ESTIMATORS``) — they are O(members)
    scalar work (ref src/average_spectrum_clustering.py:106-148).
    """
    return jax.vmap(
        lambda a, b, c, d, e: _gap_average_cluster(a, b, c, d, e, config)
    )(mz, intensity, peak_mask, member_mask, n_members)
