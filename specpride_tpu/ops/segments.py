"""Scatter-free sorted-run reductions (the shared core of every device kernel).

Motivation (measured on a v5 lite chip, 4M elements): XLA lowers
``jax.ops.segment_sum`` to a scatter-add, and TPU scatter-adds with
duplicate indices serialize — ~140 ms per call, which made every kernel
scatter-bound (the round-3 bench: bin-mean 1.4x, cosine pipeline 2.9x over
a single-threaded numpy oracle).  Two classic fixes also lose on this
hardware: ``lax.associative_scan``'s log-depth slice/concat program over 4M
elements did not finish compiling in 10 minutes, and diff-of-global-cumsum
costs ~3 decimal digits of f32 precision at realistic intensity scales
(the prefix magnitude dwarfs small run totals).

The structure of our data gives a cheaper exact formulation.  Every kernel
reduces RUNS of equal keys in PRE-SORTED flat arrays (the host lexsorts at
pack time), and a run is never longer than one cluster's member count
(bin-mean dedup leaves <= n_members peaks per (cluster, bin); cosine runs
are per-(spectrum, bin) duplicates).  With ``lcap`` a static power of two
>= the longest REAL run (the packer knows it exactly), a flat segmented
Hillis-Steele scan needs only log2(lcap) shift/select/add steps:

    for d in 1, 2, 4, ..., lcap/2:
        v[i] += v[i-d]   unless a run boundary lies in (i-d, i]

After the scan each element holds the sum of its run from the run's start
through itself — fp error is ~log2(run length) ulps of the RUN's own
magnitude (measured 2e-7 relative at 4M elements), and the whole thing is
dense shift/add work XLA fuses to ~0.03-0.04 ms for three value channels.
Padding sentinels form one arbitrarily long tail run whose scan values
saturate at ``lcap`` window sums — callers mask sentinel runs out by key,
so the garbage never escapes.

Run identification (start flags, run ids, bounds) is int32 cumsum +
``nonzero(size=...)`` + gathers — exact by construction and equally cheap.
"""

from __future__ import annotations

import jax.numpy as jnp


def run_starts(keys: jnp.ndarray) -> jnp.ndarray:
    """(N,) bool: element begins a new run of equal ``keys`` (keys sorted)."""
    return jnp.concatenate(
        [jnp.ones((1,), bool), keys[1:] != keys[:-1]]
    )


def run_starts2(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Run starts of the composite key (a, b) — avoids materialising a
    wider composite when two sorted channels are already at hand."""
    return jnp.concatenate(
        [jnp.ones((1,), bool), (a[1:] != a[:-1]) | (b[1:] != b[:-1])]
    )


def run_ids(starts: jnp.ndarray) -> jnp.ndarray:
    """(N,) int32 0-based run index per element (int cumsum — exact)."""
    return jnp.cumsum(starts.astype(jnp.int32)) - 1


def run_ends(starts: jnp.ndarray) -> jnp.ndarray:
    """(N,) bool: element is the last of its run."""
    return jnp.concatenate([starts[1:], jnp.ones((1,), bool)])


def run_end_positions(starts: jnp.ndarray, rcap: int) -> jnp.ndarray:
    """(rcap,) int32 end element position of each run, in run order.

    ``rcap`` (static) must be >= the true run count INCLUDING any sentinel
    tail run; surplus entries replicate the fill position ``n - 1`` and
    must be masked by the caller (by the key at the end position — callers
    know both the exact run count and the sentinel host-side)."""
    n = starts.shape[0]
    (endpos,) = jnp.nonzero(run_ends(starts), size=rcap, fill_value=n - 1)
    return endpos.astype(jnp.int32)


def _seg_scan_combine(
    starts: jnp.ndarray,  # (..., N) bool run starts, scan along LAST axis
    values: tuple[jnp.ndarray, ...],  # each (..., N)
    lcap: int,  # static pow2 >= longest real run
    combine,  # elementwise associative op (operator.add / operator.or_)
) -> tuple[jnp.ndarray, ...]:
    """Shared Hillis-Steele core of every segmented scan: one flag
    evolution, log2(lcap) shift/select/combine steps per channel, along
    the last axis (1-D flat layouts and (B, K) bucketized rows alike)."""
    lead = starts.shape[:-1]
    f = starts
    vs = list(values)
    d = 1
    while d < lcap:
        fs = jnp.concatenate(
            [jnp.ones(lead + (d,), bool), f[..., :-d]], axis=-1
        )
        vs = [
            jnp.where(
                f, v,
                combine(
                    v,
                    jnp.concatenate(
                        [jnp.zeros(lead + (d,), v.dtype), v[..., :-d]],
                        axis=-1,
                    ),
                ),
            )
            for v in vs
        ]
        f = f | fs
        d *= 2
    return tuple(vs)


def seg_scan(
    starts: jnp.ndarray,  # (..., N) bool run starts, scan along last axis
    values: tuple[jnp.ndarray, ...],  # each (..., N)
    lcap: int,  # static pow2 >= longest real run
) -> tuple[jnp.ndarray, ...]:
    """Segmented inclusive prefix per channel: element i gets the sum of
    its run from the run start through i (runs longer than ``lcap`` — only
    the padding sentinel run, per the packer's contract — get windowed
    partial sums; callers mask those runs out).  Works on flat (N,) layouts
    and (B, K) bucketized rows alike (scan along the last axis); row-local
    shifts stay shard-local under a cluster-axis mesh, where a flattened
    1-D scan would halo-exchange at every step."""
    import operator

    return _seg_scan_combine(starts, values, lcap, operator.add)


def run_starts2d(keys: jnp.ndarray) -> jnp.ndarray:
    """(B, K) bool: element begins a new run within its ROW (keys sorted
    per row; column 0 always starts)."""
    first = jnp.ones((keys.shape[0], 1), bool)
    return jnp.concatenate([first, keys[:, 1:] != keys[:, :-1]], axis=1)


def run_ends2d(starts: jnp.ndarray) -> jnp.ndarray:
    """(B, K) bool: element is the last of its within-row run."""
    last = jnp.ones((starts.shape[0], 1), bool)
    return jnp.concatenate([starts[:, 1:], last], axis=1)


def seg_scan_or(
    starts: jnp.ndarray,  # (N,) bool run starts
    values: tuple[jnp.ndarray, ...],  # each (N,) integer bitmasks
    lcap: int,  # static pow2 >= longest real run
) -> tuple[jnp.ndarray, ...]:
    """Segmented inclusive bitwise-OR prefix (OR is associative and
    idempotent, so windowed saturation on over-long sentinel runs is
    harmless).  Used to accumulate per-run member presence bitmasks
    without a scatter."""
    import operator

    return _seg_scan_combine(starts, values, lcap, operator.or_)


def run_sums(
    starts: jnp.ndarray,  # (N,) bool run starts (sorted keys)
    values: tuple[jnp.ndarray, ...],  # each (N,) f32
    rcap: int,  # static pow2 >= run count (incl. sentinel run)
    lcap: int,  # static pow2 >= longest real run
) -> tuple[tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Per-run totals for several value channels at once.

    Returns ``(totals_per_channel, endpos)`` — totals are (rcap,) in run
    order; ``endpos`` indexes the flat element axis (use it to fetch each
    run's key, e.g. for sentinel masking)."""
    endpos = run_end_positions(starts, rcap)
    prefixes = seg_scan(starts, values, lcap)
    return tuple(cs[endpos] for cs in prefixes), endpos
