"""Segmented stable argsort: multithreaded C++ when built, numpy fallback.

Pack-time sorts are per-segment (clusters / spectra), so a global
``np.lexsort`` over composite keys wastes both the segment structure and
every core but one — ~0.5 s of the round-3 pack phase.  The native path
(native/segsort.cpp) sorts segments independently across threads with the
same stable tie behavior; the fallback composes the same ordering with one
lexsort."""

from __future__ import annotations

import ctypes
import threading

import numpy as np

_LIB_NAME = "libsegsort.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    p = ctypes.POINTER
    lib.seg_argsort_i64.restype = ctypes.c_int
    lib.seg_argsort_i64.argtypes = [
        p(ctypes.c_int64), p(ctypes.c_int64),
        ctypes.c_int64, p(ctypes.c_int64), ctypes.c_int,
    ]
    lib.searchsorted_right_i32.restype = ctypes.c_int
    lib.searchsorted_right_i32.argtypes = [
        p(ctypes.c_int32), ctypes.c_int64,
        p(ctypes.c_int32), ctypes.c_int64,
        p(ctypes.c_int64), ctypes.c_int,
    ]
    return lib


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        from specpride_tpu.io.native import load_native

        _lib = load_native(_LIB_NAME, "SPECPRIDE_SEGSORT_LIB", _bind)
        _load_failed = _lib is None
        return _lib


def seg_argsort(
    keys: np.ndarray,  # (N,) int64 (segment-local sort keys)
    offsets: np.ndarray,  # (S + 1,) int64 segment extents
    seg_of_elem: np.ndarray | None = None,  # (N,) fallback lexsort channel
) -> np.ndarray:
    """(N,) GLOBAL indices: per segment, a stable argsort of its keys.

    ``seg_of_elem`` is only needed by the numpy fallback (one lexsort over
    (seg, key)); when omitted it is derived from ``offsets``."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lib = _load()
    if lib is not None:
        order = np.empty(keys.size, dtype=np.int64)
        p = ctypes.POINTER(ctypes.c_int64)
        rc = lib.seg_argsort_i64(
            keys.ctypes.data_as(p), offsets.ctypes.data_as(p),
            offsets.size - 1, order.ctypes.data_as(p), 0,
        )
        if rc == 0:
            return order
    if seg_of_elem is None:
        seg_of_elem = np.repeat(
            np.arange(offsets.size - 1, dtype=np.int64), np.diff(offsets)
        )
    return np.lexsort((keys, seg_of_elem))


def searchsorted_right_i32(keys: np.ndarray, queries: np.ndarray) -> np.ndarray:
    """Threaded ``np.searchsorted(keys, queries, side='right')`` for int32
    arrays (numpy fallback when the native library is absent)."""
    keys = np.ascontiguousarray(keys, dtype=np.int32)
    queries = np.ascontiguousarray(queries, dtype=np.int32)
    lib = _load()
    if lib is not None:
        out = np.empty(queries.size, dtype=np.int64)
        p32 = ctypes.POINTER(ctypes.c_int32)
        p64 = ctypes.POINTER(ctypes.c_int64)
        rc = lib.searchsorted_right_i32(
            keys.ctypes.data_as(p32), keys.size,
            queries.ctypes.data_as(p32), queries.size,
            out.ctypes.data_as(p64), 0,
        )
        if rc == 0:
            return out
    return np.searchsorted(keys, queries, side="right")
