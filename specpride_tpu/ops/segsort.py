"""Segmented stable argsort: multithreaded C++ when built, numpy fallback.

Pack-time sorts are per-segment (clusters / spectra), so a global
``np.lexsort`` over composite keys wastes both the segment structure and
every core but one — ~0.5 s of the round-3 pack phase.  The native path
(native/segsort.cpp) sorts segments independently across threads with the
same stable tie behavior; the fallback composes the same ordering with one
lexsort."""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

_LIB_NAME = "libsegsort.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        from specpride_tpu.io import native as _io_native

        _io_native.ensure_built()
        here = os.path.dirname(os.path.abspath(__file__))
        repo_root = os.path.dirname(os.path.dirname(here))
        paths = [os.path.join(repo_root, "native", _LIB_NAME)]
        env = os.environ.get("SPECPRIDE_SEGSORT_LIB")
        if env:
            paths.insert(0, env)
        for path in paths:
            if os.path.exists(path):
                try:
                    lib = ctypes.CDLL(path)
                    p = ctypes.POINTER
                    lib.seg_argsort_i64.restype = ctypes.c_int
                    lib.seg_argsort_i64.argtypes = [
                        p(ctypes.c_int64), p(ctypes.c_int64),
                        ctypes.c_int64, p(ctypes.c_int64), ctypes.c_int,
                    ]
                    _lib = lib
                    return _lib
                except OSError:
                    continue
        _load_failed = True
        return None


def seg_argsort(
    keys: np.ndarray,  # (N,) int64 (segment-local sort keys)
    offsets: np.ndarray,  # (S + 1,) int64 segment extents
    seg_of_elem: np.ndarray | None = None,  # (N,) fallback lexsort channel
) -> np.ndarray:
    """(N,) GLOBAL indices: per segment, a stable argsort of its keys.

    ``seg_of_elem`` is only needed by the numpy fallback (one lexsort over
    (seg, key)); when omitted it is derived from ``offsets``."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lib = _load()
    if lib is not None:
        order = np.empty(keys.size, dtype=np.int64)
        p = ctypes.POINTER(ctypes.c_int64)
        rc = lib.seg_argsort_i64(
            keys.ctypes.data_as(p), offsets.ctypes.data_as(p),
            offsets.size - 1, order.ctypes.data_as(p), 0,
        )
        if rc == 0:
            return order
    if seg_of_elem is None:
        seg_of_elem = np.repeat(
            np.arange(offsets.size - 1, dtype=np.int64), np.diff(offsets)
        )
    return np.lexsort((keys, seg_of_elem))
