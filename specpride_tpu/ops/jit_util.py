"""Shared jit construction helpers for the device kernels.

Buffer donation (ROADMAP item 5): every packed input array a dispatch
ships is consumed exactly once — the chunk loop never reads a shipped
buffer again after the kernel call — so donating the inputs lets XLA
reuse their device memory for the kernel's outputs instead of keeping
both resident across the dispatch.  On accelerators that honor
input-output aliasing this halves the chunk loop's peak device
footprint for the big (B, K)/(N,) channels; on CPU (and for host numpy
inputs jax transfers implicitly) donation is a documented no-op — jax
warns "Some donated buffers were not usable", which would fire once per
dispatch, so the filter below silences exactly that message.

``jit_pair`` builds the plain and donating twins of one kernel from the
same underlying function, so the two can never drift semantically: the
backend picks per call via its ``donate`` field (``--no-donate`` is the
escape hatch), and the warmup registry rebuilds whichever variant the
run will dispatch.

jax's "Some donated buffers were not usable" warning is deliberately
NOT filtered here: the backend already resolves donation off on
CPU-only hosts (where it would always fire), so on accelerator hosts
the warning is the one signal that a donated buffer silently stopped
aliasing — exactly the regression an operator must see.
"""

from __future__ import annotations

import jax


def jit_pair(fn, static_argnames, donate_argnums):
    """``(plain, donated)`` jitted twins of ``fn``.

    ``donate_argnums`` must cover only the array arguments (the static
    ones are keyword-bound via ``static_argnames`` at every call site).
    Each twin owns its own jit cache; call sites must pick ONE per run
    (the persistent compile cache keys include the aliasing spec, so
    mixing would double the compile bill for nothing)."""
    plain = jax.jit(fn, static_argnames=static_argnames)
    donated = jax.jit(
        fn, static_argnames=static_argnames, donate_argnums=donate_argnums
    )
    return plain, donated
