"""K1: binned-mean consensus device kernel (JAX/XLA).

TPU-native replacement for the per-cluster Python loop + numpy scatter-add of
ref src/binning.py:170-231 (``combine_bin_mean``).  Pipeline (see
``data.packed.BinPackedBatch``): the host quantizes m/z to grid bins in
float64 and drops duplicate-(member, bin) peaks (the numpy buffered ``+=``
semantics, ref src/binning.py:197-199), so the device kernel is pure dense
work on K packed peaks per cluster — one stable sort by bin, segmented
reductions for per-bin member counts / intensity / m/z sums, the dynamic
quorum ``int(n_members * fraction) + 1`` (ref src/binning.py:181-183), and a
global compaction so the device→host transfer carries only real output
bytes.  The (n_bins,)-sized dense grid of the reference never materialises.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import BinMeanConfig


def _bin_mean_deduped_stats(
    mz: jax.Array,  # (K,) f32
    intensity: jax.Array,  # (K,) f32
    bins: jax.Array,  # (K,) i32, sentinel = n_bins (padding)
    n_members: jax.Array,  # () i32
    config: BinMeanConfig,
):
    """Per-cluster per-bin stats (mz mean, intensity mean, keep mask) in
    segment-id positions — the vmappable core of ``bin_mean_deduped``."""
    k = bins.shape[0]
    n_bins = config.n_bins

    order = jnp.argsort(bins, stable=True)
    sb = bins[order]
    valid = sb < n_bins

    new_bin = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (sb[1:] != sb[:-1]).astype(jnp.int32)]
    )
    seg = jnp.cumsum(new_bin)

    w = jnp.where(valid, 1.0, 0.0)
    counts = jax.ops.segment_sum(w, seg, num_segments=k, indices_are_sorted=True)
    inten_sum = jax.ops.segment_sum(
        intensity[order] * w, seg, num_segments=k, indices_are_sorted=True
    )
    mz_sum = jax.ops.segment_sum(
        mz[order] * w, seg, num_segments=k, indices_are_sorted=True
    )

    if config.apply_peak_quorum:
        quorum = jnp.floor(
            n_members.astype(jnp.float32) * config.quorum_fraction
        ) + 1.0
    else:
        quorum = jnp.float32(1.0)

    keep_bin = counts >= quorum
    safe = jnp.maximum(counts, 1.0)
    return mz_sum / safe, inten_sum / safe, keep_bin


@functools.partial(jax.jit, static_argnames=("config", "total_cap"))
def bin_mean_deduped_compact(
    mz: jax.Array,  # (B, K) f32
    intensity: jax.Array,  # (B, K) f32
    bins: jax.Array,  # (B, K) i32
    n_members: jax.Array,  # (B,) i32
    config: BinMeanConfig,
    total_cap: int,
):
    """Globally-compacted deduped binned-mean: one fused 1-D output
    ``[flat_mz (total_cap) | flat_intensity (total_cap) | n_out (B)]``.

    ``total_cap`` must be >= the batch's total surviving-bin count; the host
    computes the exact total distinct-bin bound (``quantize
    .distinct_bins_per_row``) so the D2H transfer carries only real output
    bytes — on tunneled hosts the device→host link is the pipeline
    bottleneck.  Outputs are row-major: cluster order preserved, ascending
    m/z within a cluster (the reference's grid order, ref src/binning.py:220).
    """
    b, k = mz.shape
    mz_mean, inten_mean, keep = jax.vmap(
        lambda a, c, d, e: _bin_mean_deduped_stats(a, c, d, e, config)
    )(mz, intensity, bins, n_members)

    n_out = jnp.sum(keep, axis=1).astype(jnp.float32)
    flat_keep = keep.reshape(b * k)
    (idx,) = jnp.nonzero(flat_keep, size=total_cap, fill_value=b * k)
    ok = idx < b * k
    flat_mz = jnp.where(
        ok, mz_mean.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    flat_int = jnp.where(
        ok,
        inten_mean.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0),
        0.0,
    )
    return jnp.concatenate([flat_mz, flat_int, n_out])


