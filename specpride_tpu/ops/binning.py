"""K1: binned-mean consensus device kernel (JAX/XLA).

TPU-native replacement for the per-cluster Python loop + numpy scatter-add of
ref src/binning.py:170-231 (``combine_bin_mean``).  Pipeline (see
``data.packed.BinPackedBatch``): the host quantizes m/z to grid bins in
float64, drops duplicate-(member, bin) peaks (the numpy buffered ``+=``
semantics, ref src/binning.py:197-199) and PRE-SORTS each row by bin, so
the device kernel is pure dense work on K packed peaks per cluster —
segment detection on the sorted bins, segmented reductions for per-bin
member counts / intensity / m/z sums, the dynamic quorum
``int(n_members * fraction) + 1`` (ref src/binning.py:181-183), and a
global compaction so the device→host transfer carries only real output
bytes.  The (n_bins,)-sized dense grid of the reference never materialises
and no sort runs on device (TPU sorts were the dominant kernel cost).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from specpride_tpu.config import BinMeanConfig
from specpride_tpu.ops.jit_util import jit_pair


def _bin_mean_deduped_stats(
    mz: jax.Array,  # (B, K) f32, rows PRE-SORTED by bin
    intensity: jax.Array,  # (B, K) f32, same order
    bins: jax.Array,  # (B, K) i32 NON-DECREASING, sentinel = n_bins
    n_members: jax.Array,  # (B,) i32
    config: BinMeanConfig,
    lcap: int | None = None,
):
    """Per-cluster per-bin stats (mz mean, intensity mean, keep mask) at
    RUN-END positions — the (B, K) core of ``bin_mean_deduped_compact``.

    ``bins`` must be non-decreasing per row (the packer sorts on the host
    — device-side stable sorts were the dominant kernel cost on TPU); the
    reductions are row-local segmented scans (``ops.segments.seg_scan``
    — TPU scatter-adds with duplicate indices serialize, which made the
    earlier vmapped ``segment_sum`` formulation the kernel's cost).
    ``lcap`` bounds real run lengths (dedup caps a (row, bin) run at the
    row's member count; K is always safe — the padding run may exceed
    lcap, but its windowed sums are masked out by ``valid``)."""
    from specpride_tpu.ops import segments as sg

    # reduced-precision packed inputs (--precision): upcast to the f32
    # compute dtype at entry — exact for bf16-exact m/z and for int8
    # intensity codes (the host rescales fetched means by the row scale)
    mz = mz.astype(jnp.float32)
    intensity = intensity.astype(jnp.float32)
    bins = bins.astype(jnp.int32)

    k = bins.shape[1]
    n_bins = config.n_bins

    valid = bins < n_bins
    w = jnp.where(valid, 1.0, 0.0)
    starts = sg.run_starts2d(bins)
    counts, inten_sum, mz_sum = sg.seg_scan(
        starts, (w, intensity * w, mz * w), lcap or k
    )
    is_end = sg.run_ends2d(starts)

    if config.apply_peak_quorum:
        quorum = jnp.floor(
            n_members.astype(jnp.float32) * config.quorum_fraction
        ) + 1.0
    else:
        quorum = jnp.full(bins.shape[:1], 1.0, jnp.float32)

    keep_bin = is_end & valid & (counts >= quorum[:, None])
    safe = jnp.maximum(counts, 1.0)
    return mz_sum / safe, inten_sum / safe, keep_bin


def _bin_mean_flat_intensity(
    intensity: jax.Array,  # (N,) f32, sorted by (row, bin); tail padding
    gbin: jax.Array,  # (N,) i32 row*(n_bins+1)+bin, sentinel 2**31-1
    keep_runs: jax.Array,  # (rcap,) bool HOST-computed quorum keep, in run
    #   order; False past the real runs (incl. any sentinel tail run)
    total_cap: int,
    rcap: int,  # pow2 >= run count incl. any sentinel tail run
    lcap: int,  # pow2 >= longest real run (<= max n_members after dedup)
    impl: str = "scan",  # "scan" | "pallas" | "pallas_interpret"
):
    """Intensity-only flat binned-mean: per-run intensity means compacted
    by a HOST-shipped keep mask, one (total_cap,) f32 output.

    Round-5 link economics (see ``backends.tpu_backend``): the tunneled
    H2D/D2H link is the pipeline's cost, so everything the host can
    compute exactly from its own sorted pass stays there — per-run counts,
    the oracle-exact INT quorum (the device's f32 quorum compare could
    drift at edges), per-bin m/z means (f32 reduceat in oracle
    accumulation order), and per-row output counts.  The device does the
    one heavy reduction (per-run intensity sums over millions of peaks)
    and ships back only the kept means; m/z never crosses the link at
    all.  Shipping the keep mask (one bool per run) guarantees host and
    device agree on the compaction layout by construction.

    ``impl`` selects the segmented-reduction core — the log2(lcap)-step
    XLA shift/select chain, or the fused single-pass Pallas segment-mean
    kernel (``pallas_kernels.seg_mean_pallas``); the routing table in
    the tpu backend picks per platform (Pallas is an implementation
    detail of that backend, never a user-facing mode)."""
    from specpride_tpu.ops import segments as sg

    sent = jnp.int32(2**31 - 1)
    valid = gbin != sent
    w = jnp.where(valid, 1.0, 0.0)
    starts = sg.run_starts(gbin)
    if impl == "scan":
        (counts, inten_sum), _ = sg.run_sums(
            starts, (w, intensity * w), rcap, lcap
        )
        inten_mean = inten_sum / jnp.maximum(counts, 1.0)
    else:
        from specpride_tpu.ops import pallas_kernels as pk

        n = gbin.shape[0]
        pad = pk.pad_to_block(n) - n
        # fused pass: run detection + sums + mean in one VMEM transit;
        # padding extends the sentinel tail run with zero weight
        mean_elem = pk.seg_mean_pallas(
            jnp.pad(gbin, (0, pad), constant_values=sent),
            jnp.pad(w, (0, pad)),
            jnp.pad(intensity, (0, pad)),
            interpret=(impl == "pallas_interpret"),
        )[1]
        inten_mean = mean_elem[sg.run_end_positions(starts, rcap)]
    (idx,) = jnp.nonzero(keep_runs, size=total_cap, fill_value=rcap)
    ok = idx < rcap
    return jnp.where(
        ok, inten_mean.at[idx].get(mode="fill", fill_value=0.0), 0.0
    )


bin_mean_flat_intensity, bin_mean_flat_intensity_donated = jit_pair(
    _bin_mean_flat_intensity,
    static_argnames=("total_cap", "rcap", "lcap", "impl"),
    donate_argnums=(0, 1, 2),
)


def _bin_mean_flat_q(
    codes: jax.Array,  # (N,) bf16 | int8 intensity codes, (row, bin) order
    run_start: jax.Array,  # (N,) bool — True at every (row, bin) run start
    #   AND at the first padding slot, so the tail is its own dropped run
    keep_runs: jax.Array,  # (rcap,) bool HOST-computed quorum keep
    total_cap: int,
    rcap: int,  # pow2 >= run count incl. the padding tail run
    lcap: int,  # pow2 >= longest real run (the tail run may exceed it —
    #   its windowed sums are garbage but keep_runs never selects it)
    impl: str = "scan",  # "scan" | "pallas" | "pallas_interpret"
):
    """Reduced-precision twin of ``bin_mean_flat_intensity``: the
    composite int32 ``gbin`` channel (4 B/peak) is replaced by a 1-byte
    run-start mask — the kernel only ever used gbin for run boundaries
    and padding detection, both of which the host's sorted pack pass
    already knows — and intensity ships as bf16/int8 codes (2/1 B/peak).
    H2D per peak drops 8 B -> 3 B (bf16) / 2 B (int8); int8 means are
    rescaled by the per-cluster scale on the HOST (means are linear, so
    the scale never crosses the link).

    Padding needs no weight mask: the first padding slot is marked as a
    run start, so the tail forms one run whose (garbage) mean is never
    selected by ``keep_runs`` — real runs are exactly the host's."""
    from specpride_tpu.ops import segments as sg

    x = codes.astype(jnp.float32)
    w = jnp.ones_like(x)
    starts = run_start
    if impl == "scan":
        (counts, s), _ = sg.run_sums(starts, (w, x), rcap, lcap)
        inten_mean = s / jnp.maximum(counts, 1.0)
    else:
        from specpride_tpu.ops import pallas_kernels as pk

        n = x.shape[0]
        pad = pk.pad_to_block(n) - n
        # run ids from the start mask make the 1-D keyed kernel work
        # without a key channel ever crossing the link
        key = jnp.cumsum(starts.astype(jnp.int32)) - 1
        inten_mean = pk.seg_mean_pallas(
            jnp.pad(key, (0, pad), constant_values=-1),
            jnp.pad(w, (0, pad)),
            jnp.pad(x, (0, pad)),
            interpret=(impl == "pallas_interpret"),
        )[1][sg.run_end_positions(starts, rcap)]
    (idx,) = jnp.nonzero(keep_runs, size=total_cap, fill_value=rcap)
    ok = idx < rcap
    return jnp.where(
        ok, inten_mean.at[idx].get(mode="fill", fill_value=0.0), 0.0
    )


bin_mean_flat_q, bin_mean_flat_q_donated = jit_pair(
    _bin_mean_flat_q,
    static_argnames=("total_cap", "rcap", "lcap", "impl"),
    donate_argnums=(0, 1, 2),
)


def _bin_mean_deduped_compact(
    mz: jax.Array,  # (B, K) f32
    intensity: jax.Array,  # (B, K) f32
    bins: jax.Array,  # (B, K) i32
    n_members: jax.Array,  # (B,) i32
    config: BinMeanConfig,
    total_cap: int,
    lcap: int | None = None,  # pow2 >= max members (run bound); None = K
):
    """Globally-compacted deduped binned-mean: one fused 1-D output
    ``[flat_mz (total_cap) | flat_intensity (total_cap) | n_out (B)]``.

    ``total_cap`` must be >= the batch's total surviving-bin count; the host
    computes the exact total distinct-bin bound (``quantize
    .distinct_bins_per_row``) so the D2H transfer carries only real output
    bytes — on tunneled hosts the device→host link is the pipeline
    bottleneck.  Outputs are row-major: cluster order preserved, ascending
    m/z within a cluster (the reference's grid order, ref src/binning.py:220).
    """
    b, k = mz.shape
    mz_mean, inten_mean, keep = _bin_mean_deduped_stats(
        mz, intensity, bins, n_members, config, lcap
    )

    n_out = jnp.sum(keep, axis=1).astype(jnp.float32)
    flat_keep = keep.reshape(b * k)
    (idx,) = jnp.nonzero(flat_keep, size=total_cap, fill_value=b * k)
    ok = idx < b * k
    flat_mz = jnp.where(
        ok, mz_mean.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    flat_int = jnp.where(
        ok,
        inten_mean.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0),
        0.0,
    )
    return jnp.concatenate([flat_mz, flat_int, n_out])


bin_mean_deduped_compact, bin_mean_deduped_compact_donated = jit_pair(
    _bin_mean_deduped_compact,
    static_argnames=("config", "total_cap", "lcap"),
    donate_argnums=(0, 1, 2, 3),
)


