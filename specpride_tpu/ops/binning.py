"""K1: binned-mean consensus device kernel (JAX/XLA).

TPU-native replacement for the per-cluster Python loop + numpy scatter-add of
ref src/binning.py:170-231 (``combine_bin_mean``).  Pipeline (see
``data.packed.BinPackedBatch``): the host quantizes m/z to grid bins in
float64, drops duplicate-(member, bin) peaks (the numpy buffered ``+=``
semantics, ref src/binning.py:197-199) and PRE-SORTS each row by bin, so
the device kernel is pure dense work on K packed peaks per cluster —
segment detection on the sorted bins, segmented reductions for per-bin
member counts / intensity / m/z sums, the dynamic quorum
``int(n_members * fraction) + 1`` (ref src/binning.py:181-183), and a
global compaction so the device→host transfer carries only real output
bytes.  The (n_bins,)-sized dense grid of the reference never materialises
and no sort runs on device (TPU sorts were the dominant kernel cost).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import BinMeanConfig


def _bin_mean_deduped_stats(
    mz: jax.Array,  # (K,) f32, row PRE-SORTED by bin
    intensity: jax.Array,  # (K,) f32, same order
    bins: jax.Array,  # (K,) i32 NON-DECREASING, sentinel = n_bins (padding)
    n_members: jax.Array,  # () i32
    config: BinMeanConfig,
):
    """Per-cluster per-bin stats (mz mean, intensity mean, keep mask) in
    segment-id positions — the vmappable core of ``bin_mean_deduped``.

    ``bins`` must be non-decreasing per row (the packer sorts on the host —
    device-side stable sorts were the dominant kernel cost on TPU); the
    kernel is pure segment detection + sorted segment sums."""
    k = bins.shape[0]
    n_bins = config.n_bins

    sb = bins
    valid = sb < n_bins

    new_bin = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (sb[1:] != sb[:-1]).astype(jnp.int32)]
    )
    seg = jnp.cumsum(new_bin)

    w = jnp.where(valid, 1.0, 0.0)
    counts = jax.ops.segment_sum(w, seg, num_segments=k, indices_are_sorted=True)
    inten_sum = jax.ops.segment_sum(
        intensity * w, seg, num_segments=k, indices_are_sorted=True
    )
    mz_sum = jax.ops.segment_sum(
        mz * w, seg, num_segments=k, indices_are_sorted=True
    )

    if config.apply_peak_quorum:
        quorum = jnp.floor(
            n_members.astype(jnp.float32) * config.quorum_fraction
        ) + 1.0
    else:
        quorum = jnp.float32(1.0)

    keep_bin = counts >= quorum
    safe = jnp.maximum(counts, 1.0)
    return mz_sum / safe, inten_sum / safe, keep_bin


@functools.partial(jax.jit, static_argnames=("config", "total_cap", "b_cap"))
def bin_mean_flat_compact(
    mz: jax.Array,  # (N,) f32, sorted by (row, bin); tail padding
    intensity: jax.Array,  # (N,) f32, same order
    gbin: jax.Array,  # (N,) i32 row*(n_bins+1)+bin, sentinel 2**31-1
    n_members: jax.Array,  # (b_cap,) i32, 0 past the real rows
    config: BinMeanConfig,
    total_cap: int,
    b_cap: int,
):
    """Flat zero-padding variant of ``bin_mean_deduped_compact`` (see
    ``data.packed.FlatBinBatch``): one fused 1-D output
    ``[flat_mz (total_cap) | flat_intensity (total_cap) | n_out (b_cap)]``.

    The (row, bin) composite ``gbin`` makes runs globally unique, so one
    segment pass over the whole flat batch handles every cluster at once —
    no vmap, no per-row padding, and the sentinel tail contributes
    nothing."""
    n = gbin.shape[0]
    nb1 = jnp.int32(config.n_bins + 1)
    sent = jnp.int32(2**31 - 1)
    valid = gbin < sent

    new_run = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (gbin[1:] != gbin[:-1]).astype(jnp.int32)]
    )
    seg = jnp.cumsum(new_run)
    w = jnp.where(valid, 1.0, 0.0)
    counts = jax.ops.segment_sum(w, seg, num_segments=n, indices_are_sorted=True)
    inten_sum = jax.ops.segment_sum(
        intensity * w, seg, num_segments=n, indices_are_sorted=True
    )
    mz_sum = jax.ops.segment_sum(
        mz * w, seg, num_segments=n, indices_are_sorted=True
    )

    # row of each segment (empty segments -> -1 via the sentinel input)
    row_of_elem = jnp.where(valid, gbin // nb1, -1)
    row_of_seg = jax.ops.segment_max(
        row_of_elem, seg, num_segments=n, indices_are_sorted=True
    )
    real_seg = row_of_seg >= 0

    if config.apply_peak_quorum:
        nm = n_members[jnp.clip(row_of_seg, 0, b_cap - 1)].astype(jnp.float32)
        quorum = jnp.floor(nm * config.quorum_fraction) + 1.0
    else:
        quorum = jnp.float32(1.0)
    keep = real_seg & (counts >= quorum)

    safe = jnp.maximum(counts, 1.0)
    mz_mean = mz_sum / safe
    inten_mean = inten_sum / safe

    n_out = jax.ops.segment_sum(
        jnp.where(keep, 1.0, 0.0),
        jnp.where(keep, row_of_seg, b_cap),
        num_segments=b_cap + 1,
    )[:b_cap]

    (idx,) = jnp.nonzero(keep, size=total_cap, fill_value=n)
    ok = idx < n
    flat_mz = jnp.where(
        ok, mz_mean.at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    flat_int = jnp.where(
        ok, inten_mean.at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    return jnp.concatenate([flat_mz, flat_int, n_out])


@functools.partial(jax.jit, static_argnames=("config", "total_cap"))
def bin_mean_deduped_compact(
    mz: jax.Array,  # (B, K) f32
    intensity: jax.Array,  # (B, K) f32
    bins: jax.Array,  # (B, K) i32
    n_members: jax.Array,  # (B,) i32
    config: BinMeanConfig,
    total_cap: int,
):
    """Globally-compacted deduped binned-mean: one fused 1-D output
    ``[flat_mz (total_cap) | flat_intensity (total_cap) | n_out (B)]``.

    ``total_cap`` must be >= the batch's total surviving-bin count; the host
    computes the exact total distinct-bin bound (``quantize
    .distinct_bins_per_row``) so the D2H transfer carries only real output
    bytes — on tunneled hosts the device→host link is the pipeline
    bottleneck.  Outputs are row-major: cluster order preserved, ascending
    m/z within a cluster (the reference's grid order, ref src/binning.py:220).
    """
    b, k = mz.shape
    mz_mean, inten_mean, keep = jax.vmap(
        lambda a, c, d, e: _bin_mean_deduped_stats(a, c, d, e, config)
    )(mz, intensity, bins, n_members)

    n_out = jnp.sum(keep, axis=1).astype(jnp.float32)
    flat_keep = keep.reshape(b * k)
    (idx,) = jnp.nonzero(flat_keep, size=total_cap, fill_value=b * k)
    ok = idx < b * k
    flat_mz = jnp.where(
        ok, mz_mean.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    flat_int = jnp.where(
        ok,
        inten_mean.reshape(b * k).at[idx].get(mode="fill", fill_value=0.0),
        0.0,
    )
    return jnp.concatenate([flat_mz, flat_int, n_out])


