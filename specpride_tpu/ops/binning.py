"""K1: binned-mean consensus device kernel (JAX/XLA).

TPU-native replacement for the per-cluster Python loop + numpy scatter-add of
ref src/binning.py:170-231 (``combine_bin_mean``): the whole (cluster,
member, peak) batch is one jitted program — per-member duplicate-bin
resolution via a stable sort, a flat scatter-add onto the per-cluster grid,
quorum/NaN/mean finalize, and on-device compaction of surviving bins so only
(B, K) arrays travel device→host instead of (B, n_bins) grids.

Semantics reproduced from the reference (and the numpy oracle
``backends.numpy_backend.bin_mean_consensus``):

* numpy fancy-index ``+=`` buffering — within one member, several peaks in
  the same bin collapse to the LAST occurrence (ref src/binning.py:197-199);
  here an explicit last-occurrence-per-bin mask (sort by (bin, position)).
* quorum ``int(n_members * quorum_fraction) + 1`` (ref src/binning.py:181-183)
  with n_members dynamic per cluster.
* per-bin mean m/z and mean intensity over contributing members, sub-quorum
  bins dropped (ref src/binning.py:209-222).
* mean precursor m/z over members (ref src/binning.py:224).

Bin indices arrive precomputed host-side in float64
(``ops.quantize.bin_mean_bins``) with sentinel = n_bins for out-of-range /
padded peaks; scatters use ``mode='drop'`` so sentinels vanish.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from specpride_tpu.config import BinMeanConfig


def last_occurrence_mask(bins: jax.Array, sentinel: int) -> jax.Array:
    """(P,) bool: True where a peak is the last (highest-index) occurrence of
    its bin within this member; sentinel-binned peaks are False.

    This is the explicit form of numpy's buffered fancy-index ``+=``
    (ref src/binning.py:197-199).  Stable sort by bin groups equal bins with
    original order preserved, so the last element of each run is the last
    occurrence in array order.
    """
    p = bins.shape[0]
    order = jnp.argsort(bins, stable=True)
    sorted_bins = bins[order]
    is_last = jnp.concatenate(
        [sorted_bins[:-1] != sorted_bins[1:], jnp.ones((1,), dtype=bool)]
    )
    keep_sorted = is_last & (sorted_bins < sentinel)
    return jnp.zeros((p,), dtype=bool).at[order].set(keep_sorted)


def _bin_mean_cluster(
    mz: jax.Array,  # (M, P) f32
    intensity: jax.Array,  # (M, P) f32
    bins: jax.Array,  # (M, P) i32, sentinel = n_bins
    member_mask: jax.Array,  # (M,) bool
    n_members: jax.Array,  # () i32
    precursor_mz: jax.Array,  # (M,) f32
    config: BinMeanConfig,
    out_size: int,
):
    n_bins = config.n_bins
    m, p = mz.shape

    keep = jax.vmap(lambda b: last_occurrence_mask(b, n_bins))(bins)
    flat_bins = bins.reshape(m * p)
    w = keep.reshape(m * p)

    counts = jnp.zeros((n_bins,), jnp.float32).at[flat_bins].add(
        w.astype(jnp.float32), mode="drop"
    )
    inten_sum = jnp.zeros((n_bins,), jnp.float32).at[flat_bins].add(
        jnp.where(w, intensity.reshape(m * p), 0.0), mode="drop"
    )
    mz_sum = jnp.zeros((n_bins,), jnp.float32).at[flat_bins].add(
        jnp.where(w, mz.reshape(m * p), 0.0), mode="drop"
    )

    if config.apply_peak_quorum:
        # int(n * frac) + 1, truncation toward zero (ref src/binning.py:183)
        quorum = jnp.floor(
            n_members.astype(jnp.float32) * config.quorum_fraction
        ) + 1.0
    else:
        quorum = jnp.float32(1.0)

    keep_bin = counts >= quorum
    safe_counts = jnp.where(counts > 0, counts, 1.0)
    inten_mean = inten_sum / safe_counts
    mz_mean = mz_sum / safe_counts

    (idx,) = jnp.nonzero(keep_bin, size=out_size, fill_value=n_bins)
    valid_out = idx < n_bins
    out_mz = jnp.where(valid_out, mz_mean.at[idx].get(mode="fill", fill_value=0.0), 0.0)
    out_inten = jnp.where(
        valid_out, inten_mean.at[idx].get(mode="fill", fill_value=0.0), 0.0
    )
    n_out = jnp.sum(keep_bin).astype(jnp.int32)

    denom = jnp.maximum(n_members.astype(jnp.float32), 1.0)
    prec = jnp.sum(jnp.where(member_mask, precursor_mz, 0.0)) / denom
    return out_mz, out_inten, n_out, prec


@functools.partial(jax.jit, static_argnames=("config", "out_size"))
def bin_mean_batch(
    mz: jax.Array,  # (B, M, P) f32
    intensity: jax.Array,  # (B, M, P) f32
    bins: jax.Array,  # (B, M, P) i32
    member_mask: jax.Array,  # (B, M) bool
    n_members: jax.Array,  # (B,) i32
    precursor_mz: jax.Array,  # (B, M) f32
    config: BinMeanConfig,
    out_size: int,
):
    """vmapped binned-mean consensus over a padded cluster batch.

    Returns (out_mz (B, out_size), out_intensity (B, out_size),
    n_out (B,), precursor_mz (B,)).  Valid output peaks are the first
    ``n_out[b]`` entries of row b, in ascending-bin (ascending m/z) order —
    the same order the reference emits (grid order, ref src/binning.py:220).
    """
    return jax.vmap(
        lambda a, b, c, d, e, f: _bin_mean_cluster(
            a, b, c, d, e, f, config, out_size
        )
    )(mz, intensity, bins, member_mask, n_members, precursor_mz)
