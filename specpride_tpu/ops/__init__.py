"""Device kernels (JAX/XLA + Pallas) and spectrum math.

The architectural insight from the survey (§3.5): two device kernels serve
almost every capability —

* K1 binned scatter-add (peaks → dense or compact grid): consensus binning,
  occupancy grids, cosine binning
* K2 batched gram matmul + argmin/argmax reductions: medoid selection,
  all-pairs and rep-vs-member cosine

plus K3, a sort + segment-reduction pipeline for gap-average consensus.
"""
