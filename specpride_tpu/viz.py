"""Mirror plots (C7/C8): member vs theoretical peptide, member vs consensus.

Re-designed equivalents of ref src/plot_cluster.py (member spectra mirrored
against the theoretical b/y spectrum of the identified peptide) and ref
src/plot_cluster_vs_consensus.py (members mirrored against the cluster's
representative — which is broken as written in the reference: undefined
``tspec`` at :48 plus loop-indentation bugs :24-43; this is the working
equivalent).  Pure host-side matplotlib; no spectrum_utils dependency —
fragment theory comes from ``ops.fragments``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from specpride_tpu.config import FragmentConfig
from specpride_tpu.data.peaks import Spectrum
from specpride_tpu.ops.fragments import (
    fragment_annotations,
    fragment_mzs,
    match_fragments,
)


def _normalized(intensity: np.ndarray, mode: str = "root") -> np.ndarray:
    """'root' reproduces the reference's ``scale_intensity('root')``
    preprocessing (ref src/plot_cluster.py:32)."""
    if intensity.size == 0:
        return intensity
    if mode == "root":
        v = np.sqrt(np.abs(intensity))
    else:
        v = np.abs(intensity)
    peak = v.max()
    return v / peak if peak > 0 else v


def preprocess(
    spec: Spectrum,
    min_mz: float = 100.0,
    max_mz: float = 1400.0,
    min_intensity_fraction: float = 0.05,
    max_peaks: int = 50,
) -> Spectrum:
    """The reference's plotting chain: m/z window, remove precursor peak,
    intensity filter, top-N (ref src/plot_cluster.py:29-33)."""
    keep = (spec.mz >= min_mz) & (spec.mz <= max_mz)
    keep &= np.abs(spec.mz - spec.precursor_mz) > 0.5
    mz, inten = spec.mz[keep], spec.intensity[keep]
    if inten.size:
        keep2 = inten >= min_intensity_fraction * inten.max()
        mz, inten = mz[keep2], inten[keep2]
    if inten.size > max_peaks:
        top = np.argsort(inten)[-max_peaks:]
        top.sort()
        mz, inten = mz[top], inten[top]
    return Spectrum(
        mz=mz,
        intensity=inten,
        precursor_mz=spec.precursor_mz,
        precursor_charge=spec.precursor_charge,
        rt=spec.rt,
        title=spec.title,
    )


def theoretical_spectrum(
    peptide: str,
    charge: int,
    config: FragmentConfig = FragmentConfig(),
) -> Spectrum:
    """Unit-intensity b/y theoretical spectrum
    (ref src/plot_cluster.py:36-41 via spectrum_utils internals)."""
    mzs = fragment_mzs(peptide, config.ion_types, max(1, charge - 1))
    return Spectrum(
        mz=mzs,
        intensity=np.ones_like(mzs),
        precursor_mz=0.0,
        precursor_charge=charge,
        title=f"theoretical {peptide}",
    )


def mirror_plot(
    top: Spectrum,
    bottom: Spectrum,
    ax=None,
    annotate_peptide: str | None = None,
    normalize: str = "root",
    config: FragmentConfig = FragmentConfig(),
):
    """Mirror plot: ``top`` upward, ``bottom`` downward.

    Peaks within the fragment tolerance of the annotated peptide's b/y ions
    are coloured AND labelled with the matching ion (``b3``, ``y5^2+`` —
    the visible output of the spectrum_utils plots the reference wraps,
    ref src/plot_cluster.py:33-45).  Returns the matplotlib Axes.
    """
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    if ax is None:
        _, ax = plt.subplots(figsize=(10, 5))

    if annotate_peptide:
        frags, frag_labels = fragment_annotations(
            annotate_peptide, config.ion_types, 2
        )
    else:
        frags, frag_labels = np.zeros((0,)), []

    for spec, sign in ((top, 1.0), (bottom, -1.0)):
        inten = _normalized(spec.intensity, normalize) * sign
        matched = match_fragments(spec.mz, frags, config.tol, config.tol_mode)
        for sel, color in ((~matched, "#888888"), (matched, "#d62728")):
            if np.any(sel):
                ax.vlines(
                    spec.mz[sel], 0, inten[sel], color=color, linewidth=1.0
                )
        if frags.size and np.any(matched):
            # label each matched peak with its nearest fragment's identity
            pos = np.clip(
                np.searchsorted(frags, spec.mz[matched]), 1, frags.size - 1
            )
            left, right = frags[pos - 1], frags[pos]
            nearest = np.where(
                np.abs(spec.mz[matched] - left)
                <= np.abs(spec.mz[matched] - right),
                pos - 1,
                pos,
            )
            va = "bottom" if sign > 0 else "top"
            for x, y, fi in zip(spec.mz[matched], inten[matched], nearest):
                ax.annotate(
                    frag_labels[int(fi)], (x, y), ha="center", va=va,
                    fontsize=7, color="#d62728", rotation=90,
                    textcoords="offset points",
                    xytext=(0, 2 if sign > 0 else -2),
                )

    ax.axhline(0.0, color="black", linewidth=0.8)
    ax.set_xlabel("m/z")
    ax.set_ylabel("normalized intensity")
    ax.set_ylim(-1.05, 1.05)
    ax.set_title(f"{top.title}  vs  {bottom.title}"[:120])
    return ax


def plot_cluster_vs_theoretical(
    members: Sequence[Spectrum],
    peptide: str,
    charge: int,
    out_prefix: str,
    config: FragmentConfig = FragmentConfig(),
) -> list[str]:
    """C7 (ref src/plot_cluster.py:10-47 / main.sh): one mirror plot per
    member against the theoretical peptide spectrum.  Returns file paths."""
    import matplotlib.pyplot as plt

    theo = theoretical_spectrum(peptide, charge, config)
    paths = []
    for i, member in enumerate(members):
        ax = mirror_plot(
            preprocess(member), theo, annotate_peptide=peptide, config=config
        )
        path = f"{out_prefix}_{i}.png"
        ax.figure.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(ax.figure)
        paths.append(path)
    return paths


def plot_cluster_vs_consensus(
    members: Sequence[Spectrum],
    consensus: Spectrum,
    out_prefix: str,
    config: FragmentConfig = FragmentConfig(),
) -> list[str]:
    """C8 (ref src/plot_cluster_vs_consensus.py, fixed): one mirror plot per
    member against the cluster's representative."""
    import matplotlib.pyplot as plt

    paths = []
    for i, member in enumerate(members):
        ax = mirror_plot(preprocess(member), preprocess(consensus), config=config)
        path = f"{out_prefix}_{i}.png"
        ax.figure.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(ax.figure)
        paths.append(path)
    return paths
