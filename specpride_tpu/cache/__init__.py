"""Content-addressed consensus result cache (two tiers: local LRU +
optional shared Store).  See :mod:`specpride_tpu.cache.result_cache`."""

from specpride_tpu.cache.digest import (
    cluster_digest,
    file_digest,
    result_key,
)
from specpride_tpu.cache.result_cache import (
    CACHEABLE_METHODS,
    CODE_VERSION,
    DEFAULT_MAX_MB,
    LocalTier,
    ResultCache,
    RunContext,
    SharedTier,
    active,
    configure,
    make_entry,
    reset,
    runtime_for,
    totals,
)

__all__ = [
    "CACHEABLE_METHODS",
    "CODE_VERSION",
    "DEFAULT_MAX_MB",
    "LocalTier",
    "ResultCache",
    "RunContext",
    "SharedTier",
    "active",
    "cluster_digest",
    "configure",
    "file_digest",
    "make_entry",
    "reset",
    "result_key",
    "runtime_for",
    "totals",
]
