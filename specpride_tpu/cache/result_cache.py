"""Two-tier content-addressed consensus result cache.

The result plane's memory: a bounded LOCAL tier (on-disk JSON entries,
atomic rename commits, mtime-LRU eviction under a byte cap) in front of
an optional SHARED tier speaking the ``parallel.store`` Store protocol
(``put_new``/``get`` — FsStore, the in-tree CasServer, and any future
S3/GCS adapter work unmodified), keyed by
``(cluster content digest, method, config digest, precision, schema
rev)`` — see :mod:`specpride_tpu.cache.digest`.

Design invariants, all machine-checked by tests + the ci.sh pass:

* **Byte parity.**  A hit replays the representative's stored float64
  peak bits and MGF headers exactly, so cache-on output bytes and the
  QC report equal a cache-off run's for every method x precision.  Any
  axis that could change the bytes is IN the key (content, method,
  config incl. QC configuration, precision, schema rev) — there is no
  explicit invalidation, only keys that no longer match.
* **Corruption is a miss.**  Every entry is sealed with a digest of its
  own canonical body; a read-back whose seal does not verify (torn
  write, bit rot, stale schema) is quarantined aside and reported as a
  miss — never served.
* **Crash safety.**  Local commits write a private ``*.tmp.<pid>.<tid>``
  then ``os.replace``; readers only ever open ``*.json``, so tmp debris
  from a killed writer can never parse as an entry.  The shared tier's
  ``put_new`` is create-if-absent, so concurrent ranks racing to
  populate the same key resolve to one winner and no torn doc.

The module-level singleton (``configure``/``active``/``reset``) is how
the serving daemon owns the tiers process-wide: boot configures once,
every worker lane's jobs consult the same tiers under their own
per-run :class:`RunContext` counters.  ``totals()`` aggregates across
runs for the /metrics mirror.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import tempfile
import threading

import numpy as np

from specpride_tpu.cache.digest import cluster_digest, result_key

logger = logging.getLogger("specpride.cache")

# the schema revision baked into every key: bump when the entry layout
# or replay semantics change and old entries become unservable
CODE_VERSION = "rc1"
ENTRY_VERSION = 1
DEFAULT_MAX_MB = 256
_SHARED_PREFIX = "rc-"

# read-back outcome sentinel: the entry existed but failed its seal —
# callers count it corrupt (and the local tier quarantined it) but
# treat it as a miss
CORRUPT = object()

# methods whose representative is a pure function of (cluster, config):
# exactly the batcher's shareable set.  "best" is excluded — it reads a
# per-job score table that is not part of the cluster's content.
CACHEABLE_METHODS = ("bin-mean", "gap-average", "medoid")


def _b64(a: np.ndarray) -> str:
    return base64.b64encode(
        np.ascontiguousarray(a, dtype=np.float64).tobytes()
    ).decode("ascii")


def _unb64(s: str) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype=np.float64).copy()


def encode_rep(rep) -> dict:
    """A representative Spectrum -> JSON-safe doc.  Peak arrays ride as
    base64 float64 bytes (bit-exact round trip); ``extra`` rides as an
    ordered pair list because the MGF writer emits it in insertion
    order."""
    return {
        "title": rep.title,
        "pepmass": float(rep.precursor_mz),
        "charge": int(rep.precursor_charge),
        "rt": float(rep.rt),
        "mz": _b64(rep.mz),
        "intensity": _b64(rep.intensity),
        "extra": [[str(k), str(v)] for k, v in rep.extra.items()],
    }


def decode_rep(doc: dict):
    from specpride_tpu.data.peaks import Spectrum

    return Spectrum(
        mz=_unb64(doc["mz"]),
        intensity=_unb64(doc["intensity"]),
        precursor_mz=doc["pepmass"],
        precursor_charge=doc["charge"],
        rt=doc["rt"],
        title=doc["title"],
        extra=dict(tuple(kv) for kv in doc.get("extra", [])),
    )


def _canonical(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def _seal(doc: dict) -> dict:
    body = {k: v for k, v in doc.items() if k != "seal"}
    doc["seal"] = hashlib.sha256(_canonical(body)).hexdigest()
    return doc


def _verify(doc) -> bool:
    if not isinstance(doc, dict) or doc.get("v") != ENTRY_VERSION:
        return False
    seal = doc.get("seal")
    body = {k: v for k, v in doc.items() if k != "seal"}
    return isinstance(seal, str) and \
        hashlib.sha256(_canonical(body)).hexdigest() == seal


def make_entry(key: str, rep, cluster, cosine: float | None) -> dict:
    """One sealed cache entry: the representative, its QC cosine (None
    under a QC-off config key), and enough provenance to debug with."""
    return _seal({
        "v": ENTRY_VERSION,
        "key": key,
        "cluster_id": cluster.cluster_id,
        "n_members": cluster.n_members,
        "rep": encode_rep(rep),
        "cosine": None if cosine is None else float(cosine),
    })


class _Counters:
    """Thread-safe monotone counters shared by every RunContext."""

    FIELDS = (
        "hits", "misses", "populated", "evictions", "bytes_saved",
        "shared_hits", "corrupt",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._c = dict.fromkeys(self.FIELDS, 0)

    def add(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] += n

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._c)


_totals = _Counters()


def totals() -> dict:
    """Process-lifetime counters across every run — what the /metrics
    exporter mirrors into ``specpride_result_cache_*``."""
    return _totals.snapshot()


class LocalTier:
    """Bounded on-disk LRU of sealed JSON entries.

    One file per key under ``root``; recency is the file mtime (reads
    touch), the byte cap is enforced after every put by evicting
    oldest-first.  All mutation is rename-atomic so concurrent worker
    lanes (PR 14 lane discipline) need no cross-process lock: the worst
    race is two lanes writing the same key — identical sealed bytes —
    and the loser's replace is a no-op rewrite."""

    def __init__(self, root: str, max_mb: int = DEFAULT_MAX_MB):
        self.root = root
        self.max_bytes = int(max_mb) * 1024 * 1024
        self.evictions = 0
        self.evicted_bytes = 0
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + ".json")

    def get(self, key: str):
        """The sealed entry dict, ``CORRUPT`` (quarantined aside), or
        ``None``."""
        path = self._path(key)
        try:
            with open(path, "r") as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            self._quarantine(path)
            return CORRUPT
        if not _verify(doc) or doc.get("key") != key:
            self._quarantine(path)
            return CORRUPT
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        return doc

    def _quarantine(self, path: str) -> None:
        """Move a failed entry ASIDE (never delete evidence, never
        serve it): `<name>.corrupt` in the same tier dir."""
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass
        logger.warning("result cache: quarantined corrupt entry %s", path)

    def put(self, key: str, entry: dict) -> None:
        path = self._path(key)
        fd, tmp = tempfile.mkstemp(
            dir=self.root, prefix=".tmp-", suffix=".part"
        )
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(entry, fh, separators=(",", ":"))
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._enforce_cap()

    def _entries(self) -> list[tuple[float, int, str]]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue  # tmp debris and quarantined entries never count
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, path))
        return out

    def _enforce_cap(self) -> None:
        with self._lock:
            entries = self._entries()
            total = sum(size for _, size, _ in entries)
            if total <= self.max_bytes:
                return
            entries.sort()  # oldest mtime first
            for _, size, path in entries:
                if total <= self.max_bytes:
                    break
                try:
                    os.unlink(path)
                except OSError:
                    continue
                total -= size
                self.evictions += 1
                self.evicted_bytes += size
                _totals.add("evictions")

    def info(self) -> dict:
        entries = self._entries()
        return {
            "entries": len(entries),
            "bytes": sum(size for _, size, _ in entries),
            "evictions": self.evictions,
        }


class SharedTier:
    """The fleet-shared tier: any PR 11 ``Store`` (FsStore path or
    http(s) CAS URL), entries namespaced under ``rc-``.  ``put_new``
    create-if-absent semantics make concurrent population races
    harmless; a doc that fails its seal on read-back is a miss (the
    remote copy is left in place — another reader's copy may be fine,
    and a shared store is not ours to quarantine)."""

    def __init__(self, store):
        self.store = store

    def _key(self, key: str) -> str:
        return _SHARED_PREFIX + key

    def get(self, key: str):
        try:
            got = self.store.get(self._key(key))
        except OSError as e:
            logger.warning("result cache: shared-tier get failed: %s", e)
            return None
        if got is None:
            return None
        doc = got[0]
        if not _verify(doc) or doc.get("key") != key:
            logger.warning(
                "result cache: shared entry for %s failed verification; "
                "treating as a miss", key[:16],
            )
            return CORRUPT
        return doc

    def put(self, key: str, entry: dict) -> None:
        try:
            self.store.put_new(self._key(key), entry)
        except OSError as e:
            logger.warning("result cache: shared-tier put failed: %s", e)

    def describe(self) -> str:
        d = getattr(self.store, "describe", None)
        return d() if d is not None else type(self.store).__name__


class ResultCache:
    """The two tiers composed: local first, shared on a local miss
    (backfilling local so the next lookup stays on-host)."""

    def __init__(self, local: LocalTier, shared: SharedTier | None = None):
        self.local = local
        self.shared = shared

    def lookup(self, key: str):
        """``(entry, tier)`` — tier ``"local"``/``"shared"`` — or
        ``(None, "corrupt"|"miss")``."""
        doc = self.local.get(key)
        if doc is CORRUPT:
            # fall through to the shared tier: the local copy was bad,
            # the fleet's copy may not be
            doc = None
            corrupt = True
        else:
            corrupt = False
        if doc is not None:
            return doc, "local"
        if self.shared is not None:
            doc = self.shared.get(key)
            if doc is CORRUPT:
                return None, "corrupt"
            if doc is not None:
                try:
                    self.local.put(key, doc)
                except OSError:
                    pass
                return doc, "shared"
        return None, "corrupt" if corrupt else "miss"

    def populate(self, key: str, entry: dict) -> None:
        self.local.put(key, entry)
        if self.shared is not None:
            self.shared.put(key, entry)

    def info(self) -> dict:
        out = self.local.info()
        if self.shared is not None:
            out["shared"] = self.shared.describe()
        return out


class RunContext:
    """One run's view of the cache: the key axes fixed at run start
    (method, config digest, precision) plus per-run counters — what
    rides the ``result_cache`` journal event and run_end.counters."""

    def __init__(self, cache: ResultCache, method: str, config: str,
                 precision: str):
        self.cache = cache
        self.method = method
        self.config = config
        self.precision = precision
        self.counters = _Counters()
        # eviction baseline: the local tier outlives runs in a serving
        # daemon, so the run's evict count is a delta, not the lifetime
        self._evict0 = cache.local.evictions

    def key_of(self, cluster) -> str:
        return result_key(
            cluster_digest(cluster), self.method, self.config,
            self.precision, CODE_VERSION,
        )

    def consult(self, clusters) -> dict:
        """Look every cluster up under a ``cache:consult`` trace span;
        returns ``{cluster_id: (rep_or_None, cosine, key)}`` covering
        EVERY cluster — ``rep`` is None on a miss, and the key is
        stashed so the populate path never re-digests the content."""
        from specpride_tpu.observability import tracing

        out: dict = {}
        with tracing.span("cache:consult", n_clusters=len(clusters)):
            for c in clusters:
                key = self.key_of(c)
                entry, tier = self.cache.lookup(key)
                if entry is not None:
                    rep = decode_rep(entry["rep"])
                    out[c.cluster_id] = (rep, entry.get("cosine"), key)
                    self.counters.add("hits")
                    _totals.add("hits")
                    saved = int(rep.mz.nbytes + rep.intensity.nbytes)
                    self.counters.add("bytes_saved", saved)
                    _totals.add("bytes_saved", saved)
                    if tier == "shared":
                        self.counters.add("shared_hits")
                        _totals.add("shared_hits")
                else:
                    out[c.cluster_id] = (None, None, key)
                    self.counters.add("misses")
                    _totals.add("misses")
                    if tier == "corrupt":
                        self.counters.add("corrupt")
                        _totals.add("corrupt")
        return out

    @staticmethod
    def hit_ids(consulted: dict | None) -> set:
        return {
            cid for cid, (rep, _, _) in (consulted or {}).items()
            if rep is not None
        }

    def populate(self, items) -> None:
        """Commit computed results: ``items`` is an iterable of
        ``(key, rep, cluster, cosine)``.  Exceptions are contained —
        a cache that cannot persist must never fail the run that
        already wrote its output."""
        for key, rep, cluster, cosine in items:
            try:
                self.cache.populate(key, make_entry(key, rep, cluster,
                                                    cosine))
            except Exception as e:  # noqa: BLE001 - cache is best-effort
                logger.warning(
                    "result cache: populate failed for %s: %s",
                    cluster.cluster_id, e,
                )
                continue
            self.counters.add("populated")
            _totals.add("populated")

    def snapshot(self) -> dict:
        snap = self.counters.snapshot()
        info = self.cache.local.info()
        snap["entries"] = info["entries"]
        snap["bytes"] = info["bytes"]
        snap["evictions"] = self.cache.local.evictions - self._evict0
        return snap


# -- process-wide singleton (daemon boot owns it) -----------------------

_active: ResultCache | None = None
_active_lock = threading.Lock()


def parse_spec(spec: str) -> tuple[str, int]:
    """``DIR[:MB]`` -> (dir, max_mb)."""
    path, sep, mb = spec.rpartition(":")
    if sep and mb.isdigit():
        return path, int(mb)
    return spec, DEFAULT_MAX_MB


def build(spec: str, store_url: str | None = None) -> ResultCache:
    from specpride_tpu.parallel.store import store_from_spec

    root, max_mb = parse_spec(spec)
    shared = (
        SharedTier(store_from_spec(store_url)) if store_url else None
    )
    return ResultCache(LocalTier(root, max_mb), shared)


def configure(spec: str | None, store_url: str | None = None):
    """Install (or, spec None, clear) the process-wide cache.  Returns
    the installed instance."""
    global _active
    with _active_lock:
        _active = build(spec, store_url) if spec else None
        return _active


def active() -> ResultCache | None:
    with _active_lock:
        return _active


def reset() -> None:
    """Test hook: drop the singleton and zero the process totals."""
    global _active
    with _active_lock:
        _active = None
        with _totals._lock:
            for k in _totals._c:
                _totals._c[k] = 0


def runtime_for(args, command: str, backend=None):
    """The per-run :class:`RunContext`, or ``None`` when the cache does
    not apply: no tier configured (flag or daemon singleton), a
    non-cacheable method, a config the digest machinery cannot fix, or
    a batch-member pipeline (the leader already consulted for the whole
    shared dispatch — a member consulting again would double-count and
    bypass the batch attribution)."""
    if backend is not None and getattr(backend, "is_batch_view", False):
        return None
    method = getattr(args, "method", None)
    if command not in ("consensus", "select") or \
            method not in CACHEABLE_METHODS:
        return None
    spec = getattr(args, "result_cache", None)
    if spec:
        cache = build(spec, getattr(args, "result_store", None))
    else:
        cache = active()
    if cache is None:
        return None
    from specpride_tpu.serve.batcher import config_digest

    config = config_digest(args, command)
    if config is None:
        return None
    precision = str(
        getattr(backend, "precision", None)
        or getattr(args, "precision", None) or "f32"
    )
    return RunContext(cache, method, config, precision)
