"""Canonical content digests for the result cache.

The cache key's first component is a digest of WHAT a cluster *is*, not
how it was spelled on disk: two MGF files that differ only in peak
order, float formatting (``1.5`` vs ``1.50`` vs ``1.5e0``) or file path
must produce the same digest, because the consensus result depends on
neither.  Two rules make that hold:

* peaks are sorted by ``(mz, intensity)`` before hashing — MGF peak
  lists carry no semantic order and several writers emit them unsorted;
* floats are hashed as their IEEE-754 float64 *bytes*, never their text
  representation — the parser already normalized every spelling of the
  same value to one bit pattern.

Member ORDER stays part of the digest on purpose: float reduction order
is visible in the output bits (bin-mean accumulates members in file
order), so clusters whose members were reordered are different inputs
for byte-parity purposes.  The cluster id and member titles are hashed
too — both land verbatim in the output records (a medoid representative
IS a member spectrum), so they are output-relevant content.
"""

from __future__ import annotations

import hashlib

import numpy as np

# bump when the canonicalization itself changes (sort rule, field set):
# old entries then miss by key instead of being served stale
DIGEST_VERSION = "cd1"


def _hash_floats(h, *values: float) -> None:
    h.update(np.asarray(values, dtype=np.float64).tobytes())


def spectrum_digest_into(h, s) -> None:
    """Fold one spectrum into an open hash: title, precursor fields,
    then the peak list in canonical ``(mz, intensity)`` order."""
    h.update(s.title.encode("utf-8"))
    h.update(b"\x00")
    _hash_floats(h, float(s.precursor_mz), float(s.rt))
    h.update(int(s.precursor_charge).to_bytes(4, "little", signed=True))
    mz = np.asarray(s.mz, dtype=np.float64)
    inten = np.asarray(s.intensity, dtype=np.float64)
    order = np.lexsort((inten, mz))
    h.update(mz[order].tobytes())
    h.update(inten[order].tobytes())


def cluster_digest(cluster) -> str:
    """Spelling- and peak-order-invariant digest of one cluster's
    content (hex sha256)."""
    h = hashlib.sha256()
    h.update(DIGEST_VERSION.encode("ascii"))
    h.update(cluster.cluster_id.encode("utf-8"))
    h.update(len(cluster.members).to_bytes(4, "little"))
    for s in cluster.members:
        h.update(b"\x01")  # member framing: no cross-member ambiguity
        spectrum_digest_into(h, s)
    return h.hexdigest()


def result_key(
    content: str, method: str, config: str, precision: str, schema: str
) -> str:
    """The full cache key: cluster content x method x config digest x
    packed-channel precision x entry-schema revision.  Any axis changing
    invalidates by construction — there is no explicit invalidation."""
    raw = "\x00".join((content, method, config, precision, schema))
    return hashlib.sha256(raw.encode("utf-8")).hexdigest()


def file_digest(path: str, chunk: int = 1 << 20) -> str | None:
    """Content digest of a file's bytes (hex sha256), ``None`` if it
    cannot be read — the ingest cache's copied-dataset fallback key."""
    h = hashlib.sha256()
    try:
        with open(path, "rb") as fh:
            while True:
                block = fh.read(chunk)
                if not block:
                    break
                h.update(block)
    except OSError:
        return None
    return h.hexdigest()
