"""Offline decision replay: re-run the policies over a recorded
journal and diff against what the controller actually decided.

Two independent checks per recorded ``autotune`` event:

* **decision** — rebuild the policy from the event's recorded
  ``params``, re-run the shared :func:`~.controller.evaluate` gating on
  the recorded ``signal`` snapshot, and require the same ``new`` value,
  the same ``reason`` string, and ``acted`` consistent with the
  recorded ``mode``.  This is the pure-function check: policies must be
  a function of (signal, current, params) and nothing else.

* **signal refold** — feed every preceding journal line through a fresh
  :class:`~.signals.SignalState` (the same fold the live tap ran, in
  the same order — the tap fires under the journal write lock, so file
  order IS fold order) and require the snapshot at the event's recorded
  ``clock`` to equal the recorded ``signal``.  Skipped for snapshots
  carrying a ``store`` section (the fleet supervisor's store-derived
  view is evidence, not journal-derivable).

Multi-file journals replay per process stream: rotated segments of one
journal chain into one fold, ``.part<rank>`` shards are independent
streams (each rank ran its own controller over its own journal).
"""

from __future__ import annotations

import json
import re

from specpride_tpu.autotune.controller import evaluate
from specpride_tpu.autotune.policy import policy_from_params
from specpride_tpu.autotune.signals import SignalState
from specpride_tpu.observability.journal import expand_parts, read_events

_PART_RE = re.compile(r"^(.*\.part\d+)(?:\.\d+)?$")


def _streams(path: str) -> tuple[dict[str, list[str]], list[str]]:
    """Group a journal path's files into per-process streams: rotated
    segments chain under their live file's key, rank shards split."""
    paths, warnings = expand_parts(path)
    streams: dict[str, list[str]] = {}
    for p in paths:
        m = _PART_RE.match(p)
        if m:
            key = m.group(1)
        elif re.fullmatch(r".*\.\d+", p) and p.rsplit(".", 1)[0]:
            key = p.rsplit(".", 1)[0]
        else:
            key = p
        streams.setdefault(key, []).append(p)
    return streams, warnings


def _same(a, b) -> bool:
    """Structural equality through one JSON round-trip, so a live
    payload that held numpy scalars compares equal to its file form."""
    return json.dumps(a, sort_keys=True, default=str) == json.dumps(
        b, sort_keys=True, default=str
    )


def replay_journal(path: str) -> dict:
    """Replay every ``autotune`` decision under ``path``.  Returns::

        {"decisions": N, "reproduced": N_ok, "acted": ...,
         "mismatches": [...], "refold_mismatches": [...],
         "violations": [...], "warnings": [...], "streams": M}

    ``mismatches`` non-empty means the recorded controller and this
    code disagree — a policy changed since the journal was written, or
    a decision was not the pure function it claims to be."""
    streams, warnings = _streams(path)
    result: dict = {
        "decisions": 0, "reproduced": 0, "acted": 0,
        "mismatches": [], "refold_mismatches": [],
        "violations": [], "warnings": list(warnings),
        "streams": len(streams),
    }
    for key in sorted(streams):
        state: SignalState | None = None
        last: dict = {}
        pending: list[dict] = []  # events seen before window is known
        for p in streams[key]:
            events, violations = read_events(p)
            result["violations"].extend(violations)
            for rec in events:
                if rec.get("event") != "autotune":
                    if state is None:
                        pending.append(rec)
                    else:
                        state.observe(rec)
                    continue
                signal = rec.get("signal") or {}
                if state is None:
                    state = SignalState(
                        float(signal.get("window_s") or 30.0)
                    )
                    for early in pending:
                        state.observe(early)
                    pending = []
                result["decisions"] += 1
                if rec.get("acted"):
                    result["acted"] += 1
                where = f"{p}: {rec.get('knob')} @ {rec.get('clock')}"
                ok = _check_decision(rec, last, result, where)
                if ok:
                    result["reproduced"] += 1
                if "store" not in signal:
                    refold = state.snapshot(
                        float(rec.get("clock") or 0.0)
                    )
                    if not _same(refold, signal):
                        result["refold_mismatches"].append(
                            f"{where}: refolded signal differs from "
                            f"recorded (refold {refold!r})"
                        )
                last[rec.get("knob")] = rec.get("clock")
                state.observe(rec)
    result["ok"] = (
        not result["mismatches"] and not result["refold_mismatches"]
        and not result["violations"]
    )
    return result


def _check_decision(rec: dict, last: dict, result: dict,
                    where: str) -> bool:
    knob = rec.get("knob")
    try:
        policy = policy_from_params(knob, rec.get("params"))
    except ValueError as e:
        result["mismatches"].append(f"{where}: {e}")
        return False
    got = evaluate(
        policy, rec.get("signal") or {}, rec.get("old"), last.get(knob)
    )
    if got is None:
        result["mismatches"].append(
            f"{where}: replay produced NO decision where the journal "
            f"records new={rec.get('new')!r}"
        )
        return False
    new, reason = got
    ok = True
    if new != rec.get("new"):
        result["mismatches"].append(
            f"{where}: replay new={new!r} != recorded "
            f"{rec.get('new')!r}"
        )
        ok = False
    if reason != rec.get("reason"):
        result["mismatches"].append(
            f"{where}: replay reason {reason!r} != recorded "
            f"{rec.get('reason')!r}"
        )
        ok = False
    expect_acted = rec.get("mode") == "on"
    if bool(rec.get("acted")) != expect_acted:
        result["mismatches"].append(
            f"{where}: acted={rec.get('acted')!r} inconsistent with "
            f"mode={rec.get('mode')!r}"
        )
        ok = False
    return ok


def render_replay(result: dict, out) -> None:
    """Human summary for ``specpride autotune-replay``."""
    out.write(
        f"autotune-replay: {result['decisions']} decision(s) across "
        f"{result['streams']} stream(s), {result['acted']} acted\n"
    )
    out.write(
        f"  reproduced: {result['reproduced']}/{result['decisions']}\n"
    )
    for kind in ("mismatches", "refold_mismatches", "violations",
                 "warnings"):
        for line in result[kind]:
            out.write(f"  {kind[:-2] if kind.endswith('es') else kind}:"
                      f" {line}\n")
    out.write("ok\n" if result["ok"] else "FAILED\n")
