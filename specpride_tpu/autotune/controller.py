"""The Controller: journal-tapped signal fold + policies + actuators.

One controller owns one journal's :class:`~.signals.SignalState` (it
installs itself as the journal tap) and any number of registered
policies, each bound to a ``get`` (read the live knob) and optional
``set`` (actuate it) callable on the host — the daemon's locked live
batch window, its active-lane count, the coordinator's split hint, the
fleet supervisor's spare count.

A tick runs every policy through ``Journal.emit_atomic``: the signal
snapshot, the policy evaluation and the ``autotune`` decision line are
ONE critical section with respect to the journal's write lock, so no
concurrent worker event can land between the evidence snapshot and the
decision in the file — the invariant ``specpride autotune-replay``
depends on.  Actuation happens after the line is written (an acted
decision is always journaled first), and only in mode ``on``:
``observe`` journals the would-be decision with ``acted: false``.
"""

from __future__ import annotations

import threading
import time

from specpride_tpu.autotune.signals import SignalState
from specpride_tpu.observability import logger


def evaluate(policy, signal: dict, current, last_clock):
    """Shared gating + policy evaluation — the ONE code path live ticks
    and offline replay both run, so they cannot disagree.

    Returns ``(new, reason)`` or None.  Gating order: cooldown (clock
    distance from the last JOURNALED decision on this knob), then the
    policy's pure ``decide``, then no-op and deadband suppression."""
    params = policy.params
    cooldown = float(params.get("cooldown_s", 0.0))
    now = float(signal.get("now") or 0.0)
    if last_clock is not None and now - last_clock < cooldown:
        return None
    got = policy.decide(signal, current)
    if got is None:
        return None
    new, reason = got
    if new == current:
        return None
    deadband = float(params.get("deadband", 0.0))
    if deadband > 0 and current and (
        abs(new - current) / abs(current) < deadband
    ):
        return None
    return new, reason


class Controller:
    """Mode-gated decision engine over one journal.

    ``mode``: ``observe`` (default — journal would-be decisions,
    actuate nothing) or ``on``.  ``off`` never constructs a controller
    at all: the kill switch is the absence of this object, so an off
    run is byte-identical to a controller-free one.
    """

    def __init__(
        self,
        journal,
        *,
        mode: str = "observe",
        window_s: float = 30.0,
        telemetry=None,
        clock=time.perf_counter,
    ):
        if mode not in ("observe", "on"):
            raise ValueError(
                f"autotune mode {mode!r} must be observe or on"
            )
        self.journal = journal
        self.mode = mode
        self.clock = clock
        self.telemetry = telemetry  # ServeTelemetry (or None)
        self.signals = SignalState(window_s)
        # attach WITH catch-up: records already in the file (a host may
        # journal warmup/parse spans before the controller boots) fold
        # into the signal state first, so live state == fold(file) —
        # the invariant the replay refold audit holds decisions to
        journal.attach_tap(self.signals.observe)
        # knob -> (policy, get, set|None); insertion order is tick order
        self._policies: dict = {}
        self._last: dict = {}  # knob -> snapshot clock of last decision
        self.decisions = 0
        self.acted = 0

    def register(self, policy, get, set=None) -> None:
        """Bind ``policy`` to the host's live knob accessors.  ``set``
        is only called in mode ``on``, after the decision is journaled;
        its absence makes the knob observe-only whatever the mode."""
        self._policies[policy.knob] = (policy, get, set)  # lint: ok[lane-safety] boot-time only: every register() precedes the tick thread, which reads via a list() snapshot
        if self.telemetry is not None:
            value = get()
            if isinstance(value, (int, float)):
                self.telemetry.autotune_knob.set(
                    float(value), knob=policy.knob
                )

    def tick(self, extras: dict | None = None) -> list[dict]:
        """Run every registered policy once; returns the decisions
        journaled this tick.  A policy raising is logged and skipped —
        a controller bug must degrade to 'no tuning', never take the
        serving plane down."""
        out = []
        for knob, (policy, get, set_) in list(self._policies.items()):
            try:
                rec = self.journal.emit_atomic(
                    lambda p=policy, g=get, s=set_, e=extras:
                        self._decide_locked(p, g, s, e)
                )
            except Exception:
                logger.exception("autotune: %s policy tick failed", knob)
                continue
            if rec is None:
                continue
            out.append(rec)
            if rec.get("acted") and set_ is not None:
                try:
                    set_(rec["new"])
                except Exception:
                    logger.exception(
                        "autotune: actuating %s=%r failed",
                        knob, rec.get("new"),
                    )
            if self.telemetry is not None:
                self.telemetry.autotune_decision(
                    knob=knob,
                    value=rec["new"] if rec.get("acted") else rec["old"],
                    acted=bool(rec.get("acted")),
                )
        return out

    def _decide_locked(self, policy, get, set_, extras):
        """The ``emit_atomic`` build callback: runs under the journal
        write lock, so the snapshot cannot drift before the decision
        line is written.  Returns ``(event, fields)`` or None."""
        now = self.clock()
        current = get()
        signal = self.signals.snapshot(now, extras=extras)
        decision = evaluate(
            policy, signal, current, self._last.get(policy.knob)
        )
        if decision is None:
            return None
        new, reason = decision
        acted = self.mode == "on" and set_ is not None
        self._last[policy.knob] = signal["now"]
        self.decisions += 1
        if acted:
            self.acted += 1
        return "autotune", {
            "knob": policy.knob,
            "mode": self.mode,
            "old": current,
            "new": new,
            "reason": reason,
            "signal": signal,
            "acted": acted,
            "params": dict(policy.params),
            "clock": signal["now"],
            "trace_ids": self.signals.recent_traces(),
        }

    def status(self) -> dict:
        """The live counters ``serve status`` / ``stats`` surface."""
        return {
            "mode": self.mode,
            "decisions": self.decisions,
            "acted": self.acted,
            "knobs": sorted(self._policies),
        }

    def knob_values(self) -> dict:
        """Current value of every registered knob, read through its
        live accessor — the flight recorder's bundle snapshot of the
        autotune plane at incident time.  A failing accessor reads
        None: a diagnostic dump must never raise into its host."""
        out: dict = {}
        for knob, (_policy, get, _set) in list(self._policies.items()):
            try:
                out[knob] = get()
            except Exception:  # noqa: BLE001 - diagnostic best effort
                out[knob] = None
        return out

    def close(self) -> None:
        """Detach from the journal (the host is draining).  Only THIS
        controller's tap: a flight recorder tapping the same journal
        keeps observing until its own stop."""
        self.journal.detach_tap(self.signals.observe)


class ControllerThread:
    """Background tick loop for hosts with their own threads (the
    serving daemon; elastic ranks).  The fleet supervisor ticks its
    controller synchronously from its poll loop instead."""

    def __init__(self, controller: Controller, interval: float = 1.0,
                 extras_fn=None):
        self.controller = controller
        self.interval = max(float(interval), 0.05)
        self.extras_fn = extras_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ControllerThread":
        self._thread = threading.Thread(
            target=self._run, name="autotune", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            extras = self.extras_fn() if self.extras_fn else None
            self.controller.tick(extras)

    def stop(self) -> None:
        """Stop ticking, run ONE final drain tick, then detach the tap.
        The drain tick is what makes short-lived hosts observable: an
        elastic rank that finishes its whole workload inside the first
        interval would otherwise journal no decision at all.  Called
        BEFORE the host closes its journal: a tick racing a closed
        journal would lose the decision line an operator expects to
        find."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        try:
            extras = self.extras_fn() if self.extras_fn else None
            self.controller.tick(extras)
        except Exception:
            logger.exception("autotune: drain tick failed")
        self.controller.close()
