"""Signal layer: fold the journal event stream into windowed estimates.

A :class:`SignalState` is installed as the journal's in-process tap
(``Journal.set_tap``), so it observes every record under the journal's
write lock — its fold order is exactly the file's line order.  That
makes the fold REPLAYABLE: ``specpride autotune-replay`` feeds the same
journal lines through the same fold and must land on the same
snapshots, which is the property every downstream determinism claim
rests on.  Everything here is therefore a pure function of the event
stream plus the snapshot clock: no wall-clock reads, no randomness, no
dependence on anything outside the records.

Folded sources (all already emitted by the system):

========================  ============================================
event                     estimate
========================  ============================================
``job_queued``/``job_start``  live queue depth (queued-not-started)
``job_done``              job rate, wall/queue-wait means, busy
                          seconds, SLO burn (when the daemon has --slo)
``batch_dispatch``        dispatch rate, jobs/dispatch, occupancy,
                          window wait — the coalescing yield
``heartbeat``             per-rank EWMA chunk walls (v5 ``chunk_s``)
``lease_split``           steal pressure
``span``                  per-name duration attribution (critical-path
                          hops within the window)
========================  ============================================

Every section of a snapshot carries ``age_s`` — the staleness of its
newest datum — so a policy can refuse to move a knob on stale evidence.
"""

from __future__ import annotations

import collections


def _r(x) -> float:
    """One rounding rule for every float that lands in a snapshot: six
    decimals is beyond any signal's real precision and survives a JSON
    round-trip exactly, so live and replayed snapshots compare equal."""
    return round(float(x), 6)


class SignalState:
    """Windowed fold of one process's journal stream.

    Not internally locked: the journal calls :meth:`observe` under its
    own write lock, and the controller snapshots inside
    ``Journal.emit_atomic`` — under the same lock — so fold and
    snapshot are already serialized by the journal.  (Replay is
    single-threaded.)"""

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        # queue depth is a counter fold, not a windowed series: admitted
        # jobs that have not started yet, whatever their age
        self.queued = 0
        self._jobs: collections.deque = collections.deque()
        self._dispatches: collections.deque = collections.deque()
        self._beats: dict = {}  # rank -> (mono, chunk_s|None)
        self._spans: collections.deque = collections.deque()
        self._splits: collections.deque = collections.deque()
        self._traces: collections.deque = collections.deque(maxlen=8)

    # -- the journal tap ------------------------------------------------

    def observe(self, rec) -> None:
        """Fold one journal record (the ``Journal.set_tap`` callback).
        Unknown events — including ``autotune`` itself — are ignored, so
        the fold never feeds back on the controller's own decisions."""
        if not isinstance(rec, dict):
            return
        event = rec.get("event")
        mono = rec.get("mono")
        if not isinstance(mono, (int, float)):
            return
        if event == "job_queued":
            self.queued += 1
        elif event == "job_start":
            if self.queued > 0:
                self.queued -= 1
        elif event == "job_done":
            slo_ok = rec.get("slo_ok")
            self._jobs.append((
                mono,
                float(rec.get("wall_s") or 0.0),
                float(rec.get("queue_wait_s") or 0.0),
                rec.get("status"),
                slo_ok if isinstance(slo_ok, bool) else None,
            ))
            tid = rec.get("trace_id")
            if tid:
                self._traces.append(tid)
        elif event == "batch_dispatch":
            occ = rec.get("bucket_occupancy_frac")
            self._dispatches.append((
                mono,
                int(rec.get("n_jobs") or 0),
                float(rec.get("window_wait_s") or 0.0),
                float(occ) if isinstance(occ, (int, float)) else None,
            ))
            for tid in rec.get("trace_ids") or ():
                if tid:
                    self._traces.append(tid)
        elif event == "heartbeat":
            chunk_s = rec.get("chunk_s")
            self._beats[rec.get("rank")] = (
                mono,
                float(chunk_s)
                if isinstance(chunk_s, (int, float)) else None,
            )
        elif event == "lease_split":
            self._splits.append(mono)
        elif event == "span":
            name = rec.get("name")
            dur = rec.get("dur_s")
            if isinstance(name, str) and isinstance(dur, (int, float)):
                self._spans.append((mono, name, float(dur)))

    def recent_traces(self, n: int = 4) -> list:
        """The newest ``n`` distinct trace ids the fold has seen — the
        exemplars an ``autotune`` event cites as evidence."""
        out: list = []
        for tid in reversed(self._traces):
            if tid not in out:
                out.append(tid)
            if len(out) >= n:
                break
        out.reverse()
        return out

    # -- snapshots ------------------------------------------------------

    def snapshot(self, now: float, extras: dict | None = None) -> dict:
        """The windowed estimate at monotonic time ``now`` — the
        ``signal`` payload an ``autotune`` event records verbatim.
        ``extras`` (the fleet supervisor's store-derived view) rides
        along under ``"store"``: it is recorded evidence like the rest,
        but not journal-derivable, so replay re-uses the recorded copy."""
        # round FIRST: every age_s below must derive from the exact
        # clock the record carries, or replay (which only has the
        # recorded 6-decimal "now") lands 1 µs off and the refold
        # audit flags a false mismatch
        now = _r(now)
        cut = now - self.window_s
        for dq in (self._jobs, self._dispatches, self._spans):
            while dq and dq[0][0] < cut:
                dq.popleft()
        while self._splits and self._splits[0] < cut:
            self._splits.popleft()

        snap: dict = {
            "now": _r(now),
            "window_s": _r(self.window_s),
            "queue_depth": int(self.queued),
        }

        if self._jobs:
            walls = [w for _, w, _, _, _ in self._jobs]
            waits = [q for _, _, q, _, _ in self._jobs]
            slo = [ok for _, _, _, _, ok in self._jobs if ok is not None]
            snap["jobs"] = {
                "n": len(self._jobs),
                "done": sum(
                    1 for _, _, _, s, _ in self._jobs if s == "done"
                ),
                "wall_mean_s": _r(sum(walls) / len(walls)),
                "wait_mean_s": _r(sum(waits) / len(waits)),
                "busy_s": _r(sum(walls)),
                "slo_jobs": len(slo),
                "slo_breaches": sum(1 for ok in slo if not ok),
                "age_s": _r(now - self._jobs[-1][0]),
            }
        if self._dispatches:
            njobs = [n for _, n, _, _ in self._dispatches]
            waits = [w for _, _, w, _ in self._dispatches]
            occs = [o for _, _, _, o in self._dispatches if o is not None]
            snap["batch"] = {
                "n": len(self._dispatches),
                "jobs_mean": _r(sum(njobs) / len(njobs)),
                "solo": sum(1 for n in njobs if n <= 1),
                "window_wait_mean_s": _r(sum(waits) / len(waits)),
                "age_s": _r(now - self._dispatches[-1][0]),
            }
            if occs:
                snap["batch"]["occupancy_mean"] = _r(
                    sum(occs) / len(occs)
                )
        if self._beats:
            fresh = [
                (mono, cs) for mono, cs in self._beats.values()
                if mono >= cut and cs is not None
            ]
            hb: dict = {
                "ranks": len(self._beats),
                "stale_ranks": sum(
                    1 for mono, _ in self._beats.values() if mono < cut
                ),
            }
            if fresh:
                walls = [cs for _, cs in fresh]
                hb["chunk_s_mean"] = _r(sum(walls) / len(walls))
                hb["chunk_s_max"] = _r(max(walls))
                hb["age_s"] = _r(now - max(mono for mono, _ in fresh))
            snap["heartbeats"] = hb
        if self._splits:
            snap["steal"] = {
                "splits": len(self._splits),
                "age_s": _r(now - self._splits[-1]),
            }
        if self._spans:
            totals: dict = {}
            for _, name, dur in self._spans:
                totals[name] = totals.get(name, 0.0) + dur
            top = sorted(
                totals.items(), key=lambda kv: (-kv[1], kv[0])
            )[:3]
            snap["spans"] = {
                "top": [[name, _r(total)] for name, total in top]
            }
        if extras:
            snap["store"] = dict(extras)
        return snap
