"""Closed-loop autotune: a telemetry-driven controller that tunes the
serving and elastic planes from their own journals (ROADMAP item 8).

The observability plane already carries every signal an operator reads
before moving a knob — queue depth, batch occupancy and window wait,
SLO burn, per-rank EWMA chunk walls, trace span attribution.  This
package closes the loop: a :class:`~.signals.SignalState` folds those
events into windowed estimates, pure :mod:`~.policy` modules map a
snapshot to a proposed knob value, and a :class:`~.controller.Controller`
journals every decision as an evidence-carrying ``autotune`` event
(schema v5) before actuating it through the host's existing live
config path.  ``--autotune off|observe|on`` is the kill switch:
``observe`` journals would-be decisions without acting (the safe
rollout default), ``off`` leaves every output byte-identical to a
controller-free run.  ``specpride autotune-replay`` re-runs the
policies over a recorded journal and diffs the decisions, so the
controller's behavior is itself reviewable offline.
"""

from specpride_tpu.autotune.controller import (  # noqa: F401
    Controller,
    ControllerThread,
    evaluate,
)
from specpride_tpu.autotune.policy import (  # noqa: F401
    MODES,
    BatchWindowPolicy,
    ElasticRangePolicy,
    FleetSparesPolicy,
    WorkerPolicy,
    parse_clamp,
    policy_from_params,
)
from specpride_tpu.autotune.signals import SignalState  # noqa: F401
