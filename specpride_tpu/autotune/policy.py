"""Policy modules: pure functions from a signal snapshot to a proposed
knob value.

Every policy is a small object with a ``knob`` name, a ``params`` dict
(clamps, thresholds, hysteresis — recorded verbatim on each ``autotune``
event, so replay can rebuild the policy without the original CLI
flags), and one method::

    decide(signal, current) -> (new_value, reason) | None

``decide`` must be PURE over ``(signal, current, params)``: no clocks,
no randomness, no hidden state.  Hysteresis lives in the shared gating
(:func:`specpride_tpu.autotune.controller.evaluate`): a per-knob
``cooldown_s`` measured against the snapshot clock of the last
journaled decision, and a relative ``deadband`` below which a proposed
change is dropped — both derivable from the recorded decisions alone,
which is what keeps ``autotune-replay`` exact.
"""

from __future__ import annotations

MODES = ("off", "observe", "on")


def parse_clamp(spec: str, what: str = "clamp") -> tuple[float, float]:
    """``LO:HI`` -> ``(lo, hi)`` with ``0 <= lo <= hi``.  ``ValueError``
    on anything else — the CLI turns it into a usage error at boot,
    never mid-serve (same convention as ``--slo``/``--quota``)."""
    lo_s, sep, hi_s = spec.partition(":")
    if not sep:
        raise ValueError(f"{what} {spec!r} is not LO:HI")
    try:
        lo, hi = float(lo_s), float(hi_s)
    except ValueError:
        raise ValueError(
            f"{what} {spec!r}: bounds must be numbers"
        ) from None
    if not (0 <= lo <= hi):
        raise ValueError(f"{what} {spec!r}: need 0 <= LO <= HI")
    return lo, hi


class BatchWindowPolicy:
    """``--batch-window`` (ms) from queue depth + coalescing yield.

    Widen (double, from the clamp floor when off) while admitted jobs
    are stacking up — a deep queue is exactly the regime where a longer
    collection window converts queue wait into shared dispatches.
    Shrink (halve toward the floor) when the queue is idle and recent
    dispatches ran solo anyway: then the window is pure added latency
    on every lone job."""

    knob = "batch_window_ms"

    def __init__(self, lo_ms: float = 0.0, hi_ms: float = 50.0,
                 queue_hi: int = 3, cooldown_s: float = 2.0,
                 deadband: float = 0.2):
        self.params = {
            "lo_ms": float(lo_ms), "hi_ms": float(hi_ms),
            "queue_hi": int(queue_hi), "cooldown_s": float(cooldown_s),
            "deadband": float(deadband),
        }

    def decide(self, signal: dict, current):
        p = self.params
        lo, hi = p["lo_ms"], p["hi_ms"]
        depth = int(signal.get("queue_depth") or 0)
        if depth >= p["queue_hi"] and current < hi:
            # a 0 floor would make "widen from the floor" a no-op at
            # window 0 forever: seed the first widen at 1ms instead
            seed = lo if lo > 0 else min(hi, 1.0)
            new = min(hi, max(lo, current * 2.0 if current > 0 else seed))
            if new <= current:
                return None
            return round(new, 3), (
                f"queue depth {depth} >= {p['queue_hi']}: widen window "
                "to coalesce queued jobs"
            )
        batch = signal.get("batch") or {}
        jobs = signal.get("jobs") or {}
        if (
            depth == 0 and current > lo and jobs.get("n", 0) > 0
            and (not batch or batch.get("jobs_mean", 0.0) <= 1.5)
        ):
            new = max(lo, current / 2.0)
            if new >= current:
                return None
            yld = batch.get("jobs_mean")
            return round(new, 3), (
                "queue idle and window not coalescing "
                f"(jobs/dispatch {yld if yld is not None else 'n/a'}): "
                "shrink window toward floor"
            )
        return None


class WorkerPolicy:
    """Active execution lanes (within the boot-built pool) from SLO
    burn + busy fraction.  Unpark a lane while the SLO burn fraction is
    over threshold; park one when the pool is provably oversized — no
    queue, no burn, and summed busy seconds a small fraction of
    ``lanes * window``."""

    knob = "workers"

    def __init__(self, lo: int = 1, hi: int = 1,
                 burn_hi: float = 0.1, busy_lo: float = 0.25,
                 min_slo_jobs: int = 3, cooldown_s: float = 5.0):
        self.params = {
            "lo": int(lo), "hi": int(hi), "burn_hi": float(burn_hi),
            "busy_lo": float(busy_lo), "min_slo_jobs": int(min_slo_jobs),
            "cooldown_s": float(cooldown_s), "deadband": 0.0,
        }

    def decide(self, signal: dict, current):
        p = self.params
        current = int(current)
        jobs = signal.get("jobs") or {}
        slo_jobs = int(jobs.get("slo_jobs") or 0)
        breaches = int(jobs.get("slo_breaches") or 0)
        burn = breaches / slo_jobs if slo_jobs else 0.0
        if (
            slo_jobs >= p["min_slo_jobs"] and burn >= p["burn_hi"]
            and current < p["hi"]
        ):
            return current + 1, (
                f"SLO burn {breaches}/{slo_jobs} jobs in window: "
                "unpark a lane"
            )
        window = float(signal.get("window_s") or 0.0)
        busy_frac = (
            float(jobs.get("busy_s") or 0.0) / (window * current)
            if window and current else 0.0
        )
        if (
            jobs.get("n", 0) > 0 and breaches == 0
            and int(signal.get("queue_depth") or 0) == 0
            and busy_frac < p["busy_lo"] and current > p["lo"]
        ):
            return current - 1, (
                f"busy fraction {round(busy_frac, 3)} < {p['busy_lo']} "
                "with idle queue and no SLO burn: park a lane"
            )
        return None


class ElasticRangePolicy:
    """``--elastic-range`` from the heartbeat EWMA chunk walls (ROADMAP
    item 4b): size new (split-off) ranges so one range costs about
    ``target_s`` of wall time at the fleet's measured per-cluster rate.
    Already-claimed ranges are never resized — byte parity vs a serial
    run is untouched; actuation only caps how much tail a donor cedes
    on a live steal."""

    knob = "elastic_range"

    def __init__(self, lo: int = 0, hi: int = 0, target_s: float = 30.0,
                 chunk_hint: int = 1, cooldown_s: float = 5.0,
                 deadband: float = 0.25):
        self.params = {
            "lo": int(lo), "hi": int(hi), "target_s": float(target_s),
            "chunk_hint": max(int(chunk_hint), 1),
            "cooldown_s": float(cooldown_s), "deadband": float(deadband),
        }

    def decide(self, signal: dict, current):
        p = self.params
        hb = signal.get("heartbeats") or {}
        mean = hb.get("chunk_s_mean")
        if not mean or mean <= 0:
            return None  # no fresh walls: never move on stale evidence
        chunk = p["chunk_hint"]
        per_cluster = float(mean) / chunk
        desired = p["target_s"] / per_cluster
        aligned = max(int(desired // chunk), 1) * chunk
        new = int(min(p["hi"], max(p["lo"], aligned)))
        if new == int(current):
            return None
        return new, (
            f"EWMA chunk wall {mean}s over {hb.get('ranks')} rank(s) "
            f"(~{round(per_cluster, 6)}s/cluster): size split ranges "
            f"for ~{p['target_s']}s each"
        )


class FleetSparesPolicy:
    """Warm spares from steal pressure.  The supervisor's poll loop
    passes its store-derived view (live split proposals, stale
    heartbeats) as snapshot extras — recorded as evidence like every
    other signal, though not journal-derivable, so replay re-runs the
    policy on the recorded snapshot."""

    knob = "spares"

    def __init__(self, lo: int = 0, hi: int = 0, pressure_hi: int = 1,
                 cooldown_s: float = 10.0):
        self.params = {
            "lo": int(lo), "hi": int(hi),
            "pressure_hi": int(pressure_hi),
            "cooldown_s": float(cooldown_s), "deadband": 0.0,
        }

    def decide(self, signal: dict, current):
        p = self.params
        current = int(current)
        store = signal.get("store") or {}
        proposals = int(store.get("steal_proposals") or 0)
        stale = int(store.get("stale_ranks") or 0)
        if (
            (proposals >= p["pressure_hi"] or stale > 0)
            and current < p["hi"]
        ):
            return current + 1, (
                f"steal pressure (proposals={proposals}, "
                f"stale_ranks={stale}): add a warm spare"
            )
        if proposals == 0 and stale == 0 and current > p["lo"]:
            return current - 1, (
                "no steal pressure in window: retire a warm spare"
            )
        return None


_POLICY_TYPES = {
    p.knob: p for p in (
        BatchWindowPolicy, WorkerPolicy, ElasticRangePolicy,
        FleetSparesPolicy,
    )
}


def policy_from_params(knob: str, params: dict):
    """Rebuild the policy an ``autotune`` event recorded — replay's
    constructor.  Unknown params are ignored (additive schema), unknown
    knobs raise (a journal from a newer version than this reader)."""
    cls = _POLICY_TYPES.get(knob)
    if cls is None:
        raise ValueError(f"unknown autotune knob {knob!r}")
    policy = cls()
    policy.params.update({
        k: v for k, v in dict(params or {}).items()
        if k in policy.params
    })
    return policy
