"""Format conversion: build the clustered-MGF interchange file (C6).

Re-designed equivalent of ref src/convert_mgf_cluster.py: join MaxQuant
peptide IDs (msms.txt) and MaRaCluster assignments onto raw spectra, emit
spectra titled ``cluster-N;mzspec:PX:raw:scan:N[:PEPTIDE/z]``
(ref file_formats.md:5-9, ref src/convert_mgf_cluster.py:14-18).

The reference matches spectra to scans with an O(scans × spectra) linear
title scan per peptide (ref src/convert_mgf_cluster.py:74-77); both variants
here are one dict-keyed pass (survey §7 step 5).  Only scans that have BOTH
a peptide and a cluster assignment are emitted, as the reference does
(ref src/convert_mgf_cluster.py:56-86).
"""

from __future__ import annotations

import os
import re
from typing import Iterator

from specpride_tpu.config import BestSpectrumConfig
from specpride_tpu.data.peaks import Spectrum, build_title
from specpride_tpu.io.maracluster import scan_to_cluster
from specpride_tpu.io.maxquant import read_msms_peptides
from specpride_tpu.io.mgf import parse_mgf_stream, _open_text, write_mgf
from specpride_tpu.io.mzml import read_mzml_scans, write_mzml

_SCAN_IN_TITLE = re.compile(r"scan=(\d+)\s*$")


def _scan_from_mgf_title(title: str) -> int | None:
    """The reference matches ``title.endswith('scan=N')``
    (ref src/convert_mgf_cluster.py:74-77)."""
    m = _SCAN_IN_TITLE.search(title)
    return int(m.group(1)) if m else None


def convert_mgf(
    mgf_path: str | os.PathLike,
    msms_path: str | os.PathLike,
    clusters_path: str | os.PathLike,
    out_path: str | os.PathLike,
    raw_name: str,
    config: BestSpectrumConfig = BestSpectrumConfig(),
) -> int:
    """MGF variant (ref src/convert_mgf_cluster.py:47-86 convert-mq-marcluster).
    Returns the number of spectra written; streams input and output."""
    peptides = read_msms_peptides(msms_path)
    clusters = scan_to_cluster(clusters_path)

    def emit() -> Iterator[Spectrum]:
        with _open_text(mgf_path) as fh:
            for spec in parse_mgf_stream(fh):
                scan = _scan_from_mgf_title(spec.title)
                if scan is None or scan not in peptides or scan not in clusters:
                    continue
                spec.title = build_title(
                    clusters[scan],
                    config.px_accession,
                    raw_name,
                    scan,
                    peptides[scan],
                    spec.precursor_charge,
                )
                yield spec

    n = 0
    with open(os.fspath(out_path), "w", encoding="utf-8") as out:
        for spec in emit():
            write_mgf([spec], out)
            n += 1
    return n


def convert_mzml(
    mzml_path: str | os.PathLike,
    msms_path: str | os.PathLike,
    clusters_path: str | os.PathLike,
    out_path: str | os.PathLike,
    raw_name: str | None = None,
    config: BestSpectrumConfig = BestSpectrumConfig(),
) -> int:
    """mzML variant (ref src/convert_mgf_cluster.py:89-134).

    The reference stores matched spectra back to mzML with 'Cluster
    accession' / 'Peptide sequence' metaValues; ``out_path`` ending in
    ``.mgf`` writes the clustered-MGF interchange format instead (the more
    useful output — it feeds the consensus stage directly).
    """
    peptides = read_msms_peptides(msms_path)
    clusters = scan_to_cluster(clusters_path)
    wanted = set(peptides) & set(clusters)
    spectra = read_mzml_scans(mzml_path, scans=wanted)
    raw = raw_name or os.path.basename(os.fspath(mzml_path)).rsplit(".", 1)[0]

    out_path = os.fspath(out_path)
    if out_path.endswith(".mgf"):
        def emit() -> Iterator[Spectrum]:
            for scan in sorted(spectra):
                spec = spectra[scan]
                spec.title = build_title(
                    clusters[scan],
                    config.px_accession,
                    raw,
                    scan,
                    peptides[scan],
                    spec.precursor_charge,
                )
                yield spec

        write_mgf(emit(), out_path)
        return len(spectra)

    write_mzml(
        [
            (
                scan,
                spectra[scan],
                {
                    "Cluster accession": clusters[scan],
                    "Peptide sequence": peptides[scan],
                },
            )
            for scan in sorted(spectra)
        ],
        out_path,
    )
    return len(spectra)
