"""ctypes bindings for the C++ MGF fast parser (native/mgf_parser.cpp).

The C++ library parses an MGF file into flat column arrays in one pass —
replacing the reference's CPU-bound float()-per-line Python loop (ref
src/binning.py:122-167) on the hot ingest path (SURVEY.md §7 hard part d).
This module loads it over a plain C ABI (ctypes; pybind11 is deliberately
not a dependency), copies the columns into numpy arrays, and materialises
the same ``Spectrum`` objects the pure-Python parser
(``specpride_tpu.io.mgf.parse_mgf_stream``) produces — byte-for-byte
identical semantics, validated by ``tests/test_native_mgf.py``.

Loading is lazy and failure is soft: ``available()`` is False when the
shared library has not been built (``make -C native``) and every caller
falls back to the Python parser.  ``ensure_built()`` attempts the build
in-tree when a toolchain is present (used by the CLI and bench harness).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from specpride_tpu.data.peaks import Spectrum

_LIB_NAME = "libmgf_parser.so"
_lock = threading.Lock()  # guards the dlopen state (_lib/_load_failed)
_build_lock = threading.Lock()  # guards the one-shot `make` build
_lib: ctypes.CDLL | None = None
_load_failed = False
_build_attempted = False


def _candidate_paths() -> list[str]:
    here = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(os.path.dirname(here))
    paths = []
    env = os.environ.get("SPECPRIDE_NATIVE_LIB")
    if env:
        paths.append(env)
    paths.append(os.path.join(repo_root, "native", _LIB_NAME))
    paths.append(os.path.join(here, _LIB_NAME))
    return paths


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    p = ctypes.POINTER
    lib.mgf_parse.restype = ctypes.c_void_p
    lib.mgf_parse.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    for name, restype in [
        ("mgf_n_spectra", ctypes.c_int64),
        ("mgf_n_peaks", ctypes.c_int64),
        ("mgf_mz", p(ctypes.c_double)),
        ("mgf_intensity", p(ctypes.c_double)),
        ("mgf_peak_offsets", p(ctypes.c_int64)),
        ("mgf_precursor_mz", p(ctypes.c_double)),
        ("mgf_charge", p(ctypes.c_int32)),
        ("mgf_rt", p(ctypes.c_double)),
        # titles/extras are length-delimited concatenated buffers (offsets
        # give the slices) — c_void_p, NOT c_char_p, which would truncate
        # at the first NUL byte
        ("mgf_titles", ctypes.c_void_p),
        ("mgf_title_offsets", p(ctypes.c_int64)),
        ("mgf_extras", ctypes.c_void_p),
        ("mgf_extra_offsets", p(ctypes.c_int64)),
    ]:
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = [ctypes.c_void_p]
    lib.mgf_free.restype = None
    lib.mgf_free.argtypes = [ctypes.c_void_p]
    return lib


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        for path in _candidate_paths():
            if os.path.exists(path):
                try:
                    _lib = _bind(ctypes.CDLL(path))
                    return _lib
                except OSError:
                    continue
        _load_failed = True
        return None


def available() -> bool:
    """True when the C++ parser library is built and loadable."""
    return _load() is not None


# every artifact `make -C native` produces: ensure_built must not
# short-circuit on the parser alone, or a tree that built the parser
# before the other libraries existed never compiles them (and their
# callers silently fall back to single-threaded numpy paths)
_ALL_NATIVE_LIBS = (
    "libmgf_parser.so", "libgap_average.so", "libsegsort.so",
    "libcosine.so", "libmedoid.so"
)


def _native_dir() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)), "native")


def _all_built() -> bool:
    """Every library exists and is no older than the sources — a stale
    ``.so`` missing a newly added symbol would otherwise short-circuit the
    build and silently drop its callers to their numpy fallbacks."""
    d = _native_dir()
    try:
        newest_src = max(
            os.path.getmtime(os.path.join(d, f))
            for f in os.listdir(d)
            if f.endswith(".cpp") or f == "Makefile"
        )
    except (OSError, ValueError):
        return all(
            os.path.exists(os.path.join(d, n)) for n in _ALL_NATIVE_LIBS
        )
    for n in _ALL_NATIVE_LIBS:
        p = os.path.join(d, n)
        if not os.path.exists(p) or os.path.getmtime(p) < newest_src:
            return False
    return True


def ensure_built(quiet: bool = True) -> bool:
    """Build the native libraries in-tree if missing and a toolchain
    exists.

    Returns ``available()`` afterwards; never raises on build failure (the
    Python parser remains the fallback).  A failed build is attempted only
    once per process — repeated calls return False immediately.  The whole
    check-and-build is serialized under ``_build_lock`` so two threads
    reading MGFs concurrently cannot both spawn ``make`` writing the same
    ``.so`` (advisor r2); the build subprocess deliberately runs under its
    own lock, not ``_lock``, so loads already in flight aren't blocked."""
    global _load_failed, _build_attempted
    if _all_built():
        return available()
    with _build_lock:
        if _all_built():
            return available()
        if _build_attempted:
            return False
        _build_attempted = True
        native_dir = _native_dir()
        if not os.path.exists(os.path.join(native_dir, "Makefile")):
            return False
        try:
            subprocess.run(
                ["make", "-C", native_dir],
                check=True,
                capture_output=quiet,
                timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            return False
        with _lock:
            _load_failed = False  # retry the load now that the build ran
    return available()


def load_native(lib_name: str, env_var: str, bind) -> ctypes.CDLL | None:
    """Shared soft-failing loader for the sibling native libraries
    (``ops.gap_native``, ``ops.segsort``): ensure the in-tree build ran,
    then dlopen+bind the named library from the env override or
    ``native/``.  Returns None when unavailable — callers fall back to
    their numpy paths."""
    ensure_built()
    paths = []
    env = os.environ.get(env_var)
    if env:
        paths.append(env)
    paths.append(os.path.join(_native_dir(), lib_name))
    for path in paths:
        if os.path.exists(path):
            try:
                return bind(ctypes.CDLL(path))
            except (OSError, AttributeError):
                continue
    return None


def _as_array(ptr, n: int, dtype) -> np.ndarray:
    if n == 0:
        return np.zeros((0,), dtype=dtype)
    return np.array(np.ctypeslib.as_array(ptr, shape=(n,)), dtype=dtype)


def _split_concat(buf: bytes, offsets: np.ndarray) -> list[str]:
    return [
        buf[offsets[i] : offsets[i + 1]].decode("utf-8")
        for i in range(len(offsets) - 1)
    ]


def read_mgf_native(path: str) -> list[Spectrum]:
    """Parse an MGF file with the C++ library into ``Spectrum`` objects.

    Raises ``RuntimeError`` if the library is unavailable or the file fails
    to parse (same error class of failures the Python parser raises as
    ``ValueError``/``OSError`` — callers treat both as fatal input errors).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native MGF parser not built (make -C native)")
    errbuf = ctypes.create_string_buffer(256)
    handle = lib.mgf_parse(os.fspath(path).encode(), errbuf, len(errbuf))
    if not handle:
        raise RuntimeError(
            f"native MGF parse failed: {errbuf.value.decode(errors='replace')}"
        )
    try:
        n = int(lib.mgf_n_spectra(handle))
        n_peaks = int(lib.mgf_n_peaks(handle))
        mz = _as_array(lib.mgf_mz(handle), n_peaks, np.float64)
        intensity = _as_array(lib.mgf_intensity(handle), n_peaks, np.float64)
        peak_off = _as_array(lib.mgf_peak_offsets(handle), n + 1, np.int64)
        prec_mz = _as_array(lib.mgf_precursor_mz(handle), n, np.float64)
        charge = _as_array(lib.mgf_charge(handle), n, np.int32)
        rt = _as_array(lib.mgf_rt(handle), n, np.float64)
        title_off = _as_array(lib.mgf_title_offsets(handle), n + 1, np.int64)
        extra_off = _as_array(lib.mgf_extra_offsets(handle), n + 1, np.int64)
        titles_buf = ctypes.string_at(
            lib.mgf_titles(handle), int(title_off[-1]) if n else 0
        )
        extras_buf = ctypes.string_at(
            lib.mgf_extras(handle), int(extra_off[-1]) if n else 0
        )
    finally:
        lib.mgf_free(handle)

    titles = _split_concat(titles_buf, title_off)
    extras_raw = _split_concat(extras_buf, extra_off)

    spectra: list[Spectrum] = []
    for i in range(n):
        lo, hi = int(peak_off[i]), int(peak_off[i + 1])
        extra: dict[str, str] = {}
        if extras_raw[i]:
            for line in extras_raw[i].splitlines():
                key, _, value = line.partition("=")
                extra[key] = value
        spectra.append(
            Spectrum(
                mz=mz[lo:hi],
                intensity=intensity[lo:hi],
                precursor_mz=float(prec_mz[i]),
                precursor_charge=int(charge[i]),
                rt=float(rt[i]),
                title=titles[i],
                extra=extra,
            )
        )
    return spectra
