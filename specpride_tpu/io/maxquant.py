"""MaxQuant ``msms.txt`` ingest (PSM scores and peptide sequences).

The reference consumes msms.txt two ways:

* pandas read of columns 'Raw file', 'Scan number', 'Score' keyed by USI
  (ref src/best_spectrum.py:43-64 get_scores);
* positional-column read (col 1 = scan, col 7 = peptide, with the reference's
  ``words[7][1:-1]`` stripping the flanking '_' characters MaxQuant puts
  around 'Modified sequence') (ref src/convert_mgf_cluster.py:21-30
  read_peptides).

Both are reimplemented header-aware (no pandas needed on this path).
"""

from __future__ import annotations

import csv
import os


def _score_usi(
    px_accession: str, raw: str, scan: str, raw_suffix: str
) -> str:
    """The score-side USI both readers share, so MaxQuant and percolator
    sources join identically: ``mzspec:<PX>:<raw><suffix>::scan:<n>`` —
    the reference's double colon (empty index-type field,
    ref src/best_spectrum.py:61-62) is reproduced for join parity.
    ``raw_suffix`` is appended only when ``raw`` doesn't already carry it
    (MaxQuant's 'Raw file' column has no extension; user-supplied
    ``--raw-name`` values conventionally do)."""
    if raw_suffix and not raw.endswith(raw_suffix):
        raw = raw + raw_suffix
    return f"mzspec:{px_accession}:{raw}::scan:{scan}"


def _add_score(scores: dict[str, float], usi: str, score: float) -> None:
    """Max-wins on duplicate USIs (pandas idxmax over a duplicated index
    effectively compares all entries)."""
    if usi not in scores or score > scores[usi]:
        scores[usi] = score


def read_msms_scores(
    path: str | os.PathLike,
    px_accession: str = "PXD004732",
    raw_suffix: str = ".raw",
) -> dict[str, float]:
    """USI → MaxQuant PSM score (ref src/best_spectrum.py:43-64)."""
    scores: dict[str, float] = {}
    with open(path, newline="") as fh:
        reader = csv.DictReader(fh, delimiter="\t")
        for row in reader:
            usi = _score_usi(
                px_accession, row["Raw file"], row["Scan number"], raw_suffix
            )
            _add_score(scores, usi, float(row["Score"]))
    return scores


def read_percolator_scores(
    path: str | os.PathLike,
    px_accession: str = "PXD004732",
    raw_suffix: str = ".raw",
    raw_name: str | None = None,
) -> dict[str, float]:
    """USI → percolator (crux) PSM score.

    Second score source for ``select --method best``: the reference's only
    external validation pipeline rescores PSMs with crux tide-search +
    percolator (ref search.sh:4-6) but never wires the result back in —
    here the ``*.target.psms.txt`` / percolator TSV output joins through
    the same normalized-USI path as msms.txt.

    Column handling (header-aware, tab-separated): scan from ``scan``,
    score from the first of ``percolator score`` / ``xcorr score`` /
    ``score``; the raw-file name from ``raw_name`` if given, else the
    ``file`` column's basename without extension (crux records the mzML
    path there), else empty.  USIs go through the shared ``_score_usi``
    so both score sources join identically.
    """
    score_cols = ("percolator score", "xcorr score", "score")
    scores: dict[str, float] = {}
    n_rows = 0
    with open(path, newline="") as fh:
        reader = csv.DictReader(fh, delimiter="\t")
        header = reader.fieldnames or []
        for row in reader:
            n_rows += 1
            scan = row.get("scan")
            if scan is None:
                continue
            col = next((c for c in score_cols if c in row), None)
            if col is None:
                continue
            if raw_name is not None:
                raw = raw_name
            else:
                raw = os.path.basename(row.get("file", ""))
                raw = raw.rsplit(".", 1)[0] if "." in raw else raw
            usi = _score_usi(px_accession, raw, scan, raw_suffix)
            _add_score(scores, usi, float(row[col]))
    if n_rows and not scores:
        missing = [c for c in ("scan",) if c not in header]
        if not any(c in header for c in score_cols):
            missing.append("|".join(score_cols))
        raise ValueError(
            f"{path}: {n_rows} rows but none yielded a score — "
            f"missing column(s): {missing or 'unknown'}; header={header}. "
            "Expected crux/percolator TSV with a 'scan' column and one of "
            f"{score_cols} (native percolator 'PSMId' output is not "
            "supported; re-export via crux percolator)."
        )
    return scores


def read_msms_peptides(path: str | os.PathLike) -> dict[int, str]:
    """Scan number → (modified) peptide sequence.

    Positional parity with ref src/convert_mgf_cluster.py:21-30: column 1 is
    the scan, column 7 the sequence with its first and last characters
    stripped.  Later rows overwrite earlier ones for the same scan, as the
    reference dict assignment does.
    """
    peptides: dict[int, str] = {}
    with open(path) as fh:
        next(fh)  # header
        for line in fh:
            words = line.rstrip("\n").split("\t")
            if len(words) <= 7:
                continue
            scan = int(words[1])
            peptides[scan] = words[7][1:-1]
    return peptides
