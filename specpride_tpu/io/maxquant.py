"""MaxQuant ``msms.txt`` ingest (PSM scores and peptide sequences).

The reference consumes msms.txt two ways:

* pandas read of columns 'Raw file', 'Scan number', 'Score' keyed by USI
  (ref src/best_spectrum.py:43-64 get_scores);
* positional-column read (col 1 = scan, col 7 = peptide, with the reference's
  ``words[7][1:-1]`` stripping the flanking '_' characters MaxQuant puts
  around 'Modified sequence') (ref src/convert_mgf_cluster.py:21-30
  read_peptides).

Both are reimplemented header-aware (no pandas needed on this path).
"""

from __future__ import annotations

import csv
import os


def read_msms_scores(
    path: str | os.PathLike,
    px_accession: str = "PXD004732",
    raw_suffix: str = ".raw",
) -> dict[str, float]:
    """USI → MaxQuant PSM score.

    USI construction matches ref src/best_spectrum.py:61-62:
    ``mzspec:<PX>:<raw file>.raw::scan:<n>`` — note the reference's double
    colon (empty index-type field) is reproduced for join parity.
    When a USI occurs more than once, the max score wins (pandas idxmax over
    a duplicated index effectively compares all entries).
    """
    scores: dict[str, float] = {}
    with open(path, newline="") as fh:
        reader = csv.DictReader(fh, delimiter="\t")
        for row in reader:
            raw = row["Raw file"]
            scan = row["Scan number"]
            score = float(row["Score"])
            usi = f"mzspec:{px_accession}:{raw}{raw_suffix}::scan:{scan}"
            if usi not in scores or score > scores[usi]:
                scores[usi] = score
    return scores


def read_msms_peptides(path: str | os.PathLike) -> dict[int, str]:
    """Scan number → (modified) peptide sequence.

    Positional parity with ref src/convert_mgf_cluster.py:21-30: column 1 is
    the scan, column 7 the sequence with its first and last characters
    stripped.  Later rows overwrite earlier ones for the same scan, as the
    reference dict assignment does.
    """
    peptides: dict[int, str] = {}
    with open(path) as fh:
        next(fh)  # header
        for line in fh:
            words = line.rstrip("\n").split("\t")
            if len(words) <= 7:
                continue
            scan = int(words[1])
            peptides[scan] = words[7][1:-1]
    return peptides
