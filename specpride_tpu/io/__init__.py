from specpride_tpu.io.mgf import read_mgf, write_mgf, IndexedMGF
from specpride_tpu.io.maracluster import read_maracluster_clusters, scan_to_cluster
from specpride_tpu.io.maxquant import read_msms_scores, read_msms_peptides
from specpride_tpu.io.mzml import iter_mzml, read_mzml_scans, write_mzml

__all__ = [
    "read_mgf",
    "write_mgf",
    "IndexedMGF",
    "read_maracluster_clusters",
    "scan_to_cluster",
    "read_msms_scores",
    "read_msms_peptides",
    "iter_mzml",
    "read_mzml_scans",
    "write_mzml",
]
