"""MGF (Mascot Generic Format) reading and writing.

Built from scratch (pyteomics is not a dependency of this framework).
Capabilities covered, with reference provenance:

* sequential full-file read        (ref src/binning.py:122-167 hand parser)
* random access by TITLE           (ref src/average_spectrum_clustering.py:156
                                    via pyteomics ``IndexedMGF``)
* write                            (ref src/binning.py:234-245 hand writer;
                                    pyteomics ``mgf.write`` elsewhere)

Parsing accepts the clustered-MGF interchange dialect of
ref file_formats.md:3-53: BEGIN IONS / TITLE= / PEPMASS= / CHARGE=N+ /
RTINSECONDS= / SEQUENCE= / numeric peak lines "mz intensity" / END IONS.
Gzip-transparent (ref src/binning.py:72-77 handles .gz mzML the same way).

A C++ fast path (``specpride_tpu.io.native``) parses large files into flat
arrays; this module is the always-available fallback and the semantics oracle.
"""

from __future__ import annotations

import gzip
import io
import os
from typing import IO, Iterator, Sequence

import numpy as np

from specpride_tpu.data.peaks import Cluster, Spectrum, parse_title
from specpride_tpu.observability import tracing


def _open_text(path: str | os.PathLike) -> IO[str]:
    path = os.fspath(path)
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8")
    return open(path, "rt", encoding="utf-8")


def _parse_charge(value: str) -> int:
    """CHARGE=2+ / 2- / 2 → signed int (ref src/binning.py:148 strips '+')."""
    value = value.strip()
    sign = 1
    if value.endswith("+"):
        value = value.rstrip("+")
    elif value.endswith("-"):
        value = value.rstrip("-")
        sign = -1
    return sign * int(value) if value else 0


def _finish_spectrum(
    headers: dict[str, str], mzs: list[float], intensities: list[float]
) -> Spectrum:
    pepmass = headers.get("PEPMASS", "0")
    # PEPMASS may carry "mz intensity"; only the first field is the m/z
    pepmass_mz = float(pepmass.split()[0]) if pepmass.split() else 0.0
    return Spectrum(
        mz=np.array(mzs, dtype=np.float64),
        intensity=np.array(intensities, dtype=np.float64),
        precursor_mz=pepmass_mz,
        precursor_charge=_parse_charge(headers.get("CHARGE", "0")),
        rt=float(headers.get("RTINSECONDS", 0.0) or 0.0),
        title=headers.get("TITLE", ""),
        extra={k: v for k, v in headers.items()
               if k not in ("TITLE", "PEPMASS", "CHARGE", "RTINSECONDS")},
    )


def _ingest_line(
    line: str, headers: dict[str, str],
    mzs: list[float], intensities: list[float],
) -> None:
    """Fold one in-record MGF line into the accumulating record state —
    the ONE copy of the peak/header grammar both the strict and the
    quarantining parser run, so their accepted dialect can never drift."""
    if line[0].isdigit() or line[0] in "+-.":
        fields = line.split()
        if len(fields) >= 2:
            mzs.append(float(fields[0]))
            intensities.append(float(fields[1]))
        elif len(fields) == 1:
            mzs.append(float(fields[0]))
            intensities.append(0.0)
    else:
        key, sep, value = line.partition("=")
        if sep:
            headers[key.strip().upper()] = value.strip()


def _parse_block(lines: list[str]) -> Spectrum:
    """Parse one buffered BEGIN IONS..END IONS block (exclusive)."""
    headers: dict[str, str] = {}
    mzs: list[float] = []
    intensities: list[float] = []
    for line in lines:
        _ingest_line(line, headers, mzs, intensities)
    return _finish_spectrum(headers, mzs, intensities)


def _parse_mgf_quarantining(stream: IO[str], malformed) -> Iterator[Spectrum]:
    """Tolerant parse: records buffer per block; a block that fails to
    parse — or is structurally truncated (BEGIN IONS reopening an open
    record, EOF before END IONS) — goes to ``malformed(raw, reason)``
    verbatim instead of aborting the stream.  The strict path cannot
    even DETECT a truncated block: its BEGIN handler silently resets
    state, dropping the partial record on the floor."""
    block: list[str] = []
    in_ions = False
    for line in stream:
        line = line.strip()
        if not line:
            continue
        if line == "BEGIN IONS":
            if in_ions:
                malformed(
                    "\n".join(block),
                    "truncated record (BEGIN IONS inside an open record)",
                )
            block = [line]
            in_ions = True
        elif line == "END IONS":
            if in_ions:
                try:
                    spectrum = _parse_block(block[1:])
                except (ValueError, OverflowError) as e:
                    malformed(
                        "\n".join(block + [line]),
                        f"unparseable record ({e})",
                    )
                else:
                    yield spectrum
            in_ions = False
            block = []
        elif in_ions:
            block.append(line)
    if in_ions and block:
        malformed("\n".join(block), "truncated record (EOF before END IONS)")


def parse_mgf_stream(
    stream: IO[str], malformed=None
) -> Iterator[Spectrum]:
    """Yield spectra from an MGF text stream.

    ``malformed`` (optional ``callable(raw_block: str, reason: str)``)
    switches on quarantining: unparseable or truncated blocks are handed
    over raw and the stream continues — the robustness layer's
    ``Quarantine`` writes them to ``<output>.quarantine.mgf``.  Without
    it, parse errors raise exactly as before (library callers keep
    fail-fast semantics)."""
    if malformed is not None:
        yield from _parse_mgf_quarantining(stream, malformed)
        return
    headers: dict[str, str] = {}
    mzs: list[float] = []
    intensities: list[float] = []
    in_ions = False
    for line in stream:
        line = line.strip()
        if not line:
            continue
        if line == "BEGIN IONS":
            in_ions = True
            headers, mzs, intensities = {}, [], []
        elif line == "END IONS":
            if in_ions:
                yield _finish_spectrum(headers, mzs, intensities)
            in_ions = False
        elif not in_ions:
            continue
        else:
            _ingest_line(line, headers, mzs, intensities)
    return


def read_mgf(
    path: str | os.PathLike, use_native: bool | None = None, malformed=None,
) -> list[Spectrum]:
    """Read all spectra from an MGF file.

    ``use_native`` selects the C++ parser: True forces it (building it
    in-tree if needed), False forbids it, None (default) uses it only when
    the shared library is already built and loadable — library code must
    not spawn a compiler as a side effect of reading a file.  Opt in to
    auto-build on the default path with ``SPECPRIDE_NATIVE_BUILD=1`` (the
    CLI and bench harness call ``native.ensure_built()`` explicitly).

    ``malformed`` enables quarantining (see ``parse_mgf_stream``) and
    forces the Python parser.  Deliberate, not an oversight: the C++
    fast path either fails hard on damage or — worse for this mode —
    silently skips a structurally truncated block, and quarantine
    exists precisely to make such blocks auditable.  The cost is
    bounded: eager reads cap at the 256 MB streaming threshold, and
    streamed window parses take the Python parser regardless.
    """
    with tracing.span("parse:mgf", path=os.fspath(path)) as sp:
        if malformed is not None:
            with _open_text(path) as fh:
                spectra = list(parse_mgf_stream(fh, malformed=malformed))
            sp.note(n_spectra=len(spectra), parser="python-quarantine")
            return spectra
        if use_native is not False:
            try:
                from specpride_tpu.io import native

                auto_build = os.environ.get("SPECPRIDE_NATIVE_BUILD", "") == "1"
                ok = (
                    native.ensure_built()
                    if (use_native or auto_build)
                    else native.available()
                )
                if ok:
                    spectra = native.read_mgf_native(os.fspath(path))
                    sp.note(n_spectra=len(spectra), parser="native")
                    return spectra
                if use_native:
                    raise RuntimeError(
                        "native MGF parser requested but not built"
                    )
            except ImportError:
                if use_native:
                    raise
        with _open_text(path) as fh:
            spectra = list(parse_mgf_stream(fh))
        sp.note(n_spectra=len(spectra), parser="python")
        return spectra


class IndexedMGF:
    """Random access to an MGF file by TITLE.

    Capability parity with pyteomics ``IndexedMGF`` as used at
    ref src/average_spectrum_clustering.py:156-160: exposes the in-file title
    order and batch fetch by title list.  Implementation: one indexing pass
    recording byte offsets, then seeks.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        self._offsets: dict[str, tuple[int, int]] = {}
        self._titles: list[str] = []
        self._index()

    def _index(self) -> None:
        # Byte-offset scan; works on plain files (gz falls back to full read)
        if self.path.endswith(".gz"):
            self._spectra = {s.title: s for s in read_mgf(self.path, use_native=False)}
            self._titles = list(self._spectra)
            return
        self._spectra = None
        with open(self.path, "rb") as fh:
            offset = 0
            begin = -1
            title = None
            for line in fh:
                stripped = line.strip()
                if stripped == b"BEGIN IONS":
                    begin = offset
                    title = None
                elif stripped.startswith(b"TITLE="):
                    title = stripped[6:].decode("utf-8")
                elif stripped == b"END IONS" and begin >= 0:
                    end = offset + len(line)
                    key = title if title is not None else f"index={len(self._titles)}"
                    self._offsets[key] = (begin, end)
                    self._titles.append(key)
                    begin = -1
                offset += len(line)

    @property
    def titles(self) -> list[str]:
        return list(self._titles)

    def __len__(self) -> int:
        return len(self._titles)

    def __getitem__(self, key: str | Sequence[str]) -> Spectrum | list[Spectrum]:
        if isinstance(key, str):
            return self._get_one(key)
        return [self._get_one(k) for k in key]

    def _get_one(self, title: str) -> Spectrum:
        if self._spectra is not None:
            return self._spectra[title]
        begin, end = self._offsets[title]
        with open(self.path, "rb") as fh:
            fh.seek(begin)
            chunk = fh.read(end - begin).decode("utf-8")
        return next(parse_mgf_stream(io.StringIO(chunk)))


class StreamedClusters:
    """Bounded-memory, list-like cluster access over a clustered MGF.

    The reference streams clusters from an indexed MGF instead of loading
    the file (ref src/average_spectrum_clustering.py:151-160); whole-file
    ``read_mgf`` caps input size at host RAM.  One byte-offset index pass
    records every record's (title, range) WITHOUT parsing peaks; member
    spectra then parse lazily in WINDOWS of clusters, and only the current
    window stays cached — peak RSS is O(index + window), flat in file size.

    Order parity with ``read_mgf`` + ``group_into_clusters``: first-seen
    cluster order, in-file member order (scattered members supported).
    Integer indexing materialises the window containing the cluster;
    slicing returns a sub-view sharing the index.  Plain files only
    (callers fall back to eager loading for ``.gz``).
    """

    def __init__(self, path: str | os.PathLike, window: int = 512,
                 _groups=None):
        self.path = os.fspath(path)
        self.window = max(int(window), 1)
        # robustness hooks: byte ranges of structurally truncated blocks
        # found by the index scan (never indexed, so without quarantine
        # they would vanish SILENTLY), and the per-record malformed
        # callback used by window materialization (set by the CLI when
        # --on-error skip arms the quarantine; must be thread-safe — the
        # pack pool materializes windows concurrently)
        self.malformed_spans: list[tuple[int, int]] = []
        self.on_malformed = None
        if _groups is not None:
            self._groups = _groups
        else:
            records = self._scan()
            by_id: dict[str, list[tuple[int, int]]] = {}
            for title, begin, end in records:
                cid, _ = parse_title(title)
                by_id.setdefault(cid, []).append((begin, end))
            self._groups = list(by_id.items())
        # TWO cached windows keyed by window start, not one: under the
        # pipelined executor the packer thread materializes window W+1
        # ahead while the consumer may re-walk window W cluster by
        # cluster for its serial retry (--on-error skip) — a single slot
        # would ping-pong and re-parse a full window per index access.
        # ``cache_slots`` is the capacity: the pack worker pool raises it
        # to workers+1 so concurrent lookahead on distinct windows can't
        # evict each other.  Peak RSS stays O(index + cache_slots
        # windows); the lock serializes the cache against every lane.
        self.cache_slots = 2
        self._windows: dict[int, list[Cluster]] = {}
        import threading

        self._cache_lock = threading.RLock()

    @tracing.traced("parse:mgf_index")
    def _scan(self) -> list[tuple[str, int, int]]:
        records = []
        with open(self.path, "rb") as fh:
            offset = 0
            begin = -1
            title = None
            for line in fh:
                stripped = line.strip()
                if stripped == b"BEGIN IONS":
                    if begin >= 0:
                        # an open record re-begun: the partial block
                        # [begin, offset) has no END IONS — remember it
                        # so quarantine can surface it instead of the
                        # historical silent drop
                        self.malformed_spans.append((begin, offset))
                    begin = offset
                    title = None
                elif stripped.startswith(b"TITLE="):
                    title = stripped[6:].decode("utf-8")
                elif stripped == b"END IONS" and begin >= 0:
                    records.append((
                        title if title is not None
                        else f"index={len(records)}",
                        begin, offset + len(line),
                    ))
                    begin = -1
                offset += len(line)
            if begin >= 0:
                self.malformed_spans.append((begin, offset))
        return records

    def drain_malformed(self, malformed) -> int:
        """Hand every scan-detected truncated block to ``malformed(raw,
        reason)`` and forget them.  Returns the count drained."""
        with self._cache_lock:
            # pack-pool workers window-parse under the same lock; the
            # drain swap must not race a concurrent scan's appends
            spans, self.malformed_spans = self.malformed_spans, []
        with open(self.path, "rb") as fh:
            for begin, end in spans:
                fh.seek(begin)
                raw = fh.read(end - begin).decode("utf-8", errors="replace")
                malformed(
                    raw.strip(), "truncated record (no END IONS)"
                )
        return len(spans)

    @property
    def cluster_ids(self) -> list[str]:
        return [cid for cid, _ in self._groups]

    @property
    def n_spectra(self) -> int:
        return sum(len(r) for _, r in self._groups)

    def __len__(self) -> int:
        return len(self._groups)

    def __getitem__(self, key):
        if isinstance(key, slice):
            sub = StreamedClusters(
                self.path, self.window, _groups=self._groups[key]
            )
            # sub-views (multi-host shards) keep quarantining per-record
            # damage; scan-level malformed spans stay with the parent
            # (already drained once — a shard must not re-report them)
            sub.on_malformed = self.on_malformed
            return sub
        i = int(key)
        if i < 0:
            i += len(self._groups)
        if not 0 <= i < len(self._groups):
            raise IndexError(key)
        lo = (i // self.window) * self.window
        with self._cache_lock:
            cached = self._windows.get(lo)
            if cached is not None:
                # LRU touch: a window being re-walked by the consumer's
                # retry must not be the one evicted by the packer's
                # lookahead inserts (dict preserves insertion order)
                self._windows.pop(lo)
                self._windows[lo] = cached
                return cached[i - lo]
        # parse OUTSIDE the lock: holding it across a full window parse
        # would stall the other pipeline lane's cache HITS for hundreds
        # of ms — the very overlap the two-slot cache exists for.  Two
        # threads racing on the same cold window parse it twice (wasted
        # work, identical result); the re-check keeps one copy.
        parsed = self._materialize(self._groups[lo : lo + self.window])
        with self._cache_lock:
            cached = self._windows.pop(lo, parsed)
            slots = max(int(self.cache_slots), 1)
            while len(self._windows) >= slots:  # evict least-recently USED
                self._windows.pop(next(iter(self._windows)))
            self._windows[lo] = cached
            return cached[i - lo]

    def __iter__(self):
        for i in range(len(self._groups)):
            yield self[i]

    @tracing.traced("parse:mgf_window")
    def _materialize(self, groups) -> list[Cluster]:
        # merge exactly-adjacent byte ranges so a cluster-contiguous file
        # (the common convert output) reads as a handful of large spans
        ranges = sorted(
            (begin, end, cid)
            for cid, recs in groups
            for begin, end in recs
        )
        spans: list[list[int]] = []
        for begin, end, _ in ranges:
            if spans and begin == spans[-1][1]:
                spans[-1][1] = end
            else:
                spans.append([begin, end])
        members: dict[str, list[Spectrum]] = {cid: [] for cid, _ in groups}
        wanted = set(members)
        with open(self.path, "rb") as fh:
            for begin, end in spans:
                fh.seek(begin)
                chunk = fh.read(end - begin).decode("utf-8")
                for s in parse_mgf_stream(
                    io.StringIO(chunk), malformed=self.on_malformed
                ):
                    if s.cluster_id in wanted:
                        members[s.cluster_id].append(s)
        return [Cluster(cid, members[cid]) for cid, _ in groups]


def format_spectrum(spectrum: Spectrum, skip_nan: bool = True) -> str:
    """Format one spectrum as an MGF record.

    Field order TITLE / PEPMASS / RTINSECONDS / CHARGE matches the
    interchange examples (ref file_formats.md:5-9); extra headers (e.g.
    SEQUENCE=, present in the interchange example at ref file_formats.md:9)
    follow in insertion order so records round-trip; NaN-intensity peaks
    are skipped as in the reference writer (ref src/binning.py:242).
    """
    lines = ["BEGIN IONS", f"TITLE={spectrum.title}"]
    lines.append(f"PEPMASS={spectrum.precursor_mz}")
    if spectrum.rt:
        lines.append(f"RTINSECONDS={spectrum.rt}")
    z = spectrum.precursor_charge
    if z:
        lines.append(f"CHARGE={abs(z)}{'+' if z > 0 else '-'}")
    for key, value in spectrum.extra.items():
        lines.append(f"{key}={value}")
    # vectorized peak lines: float64 -> 'U32' uses the same dragon4
    # shortest repr as str()/f-strings, so output stays byte-identical to
    # the per-peak loop this replaces (measured 1.6x faster; the writer
    # was 75% of the file-to-file pipeline wall)
    mz = np.asarray(spectrum.mz, dtype=np.float64)
    inten = np.asarray(spectrum.intensity, dtype=np.float64)
    if skip_nan:
        ok = ~(np.isnan(mz) | np.isnan(inten))
        mz, inten = mz[ok], inten[ok]
    if mz.size:
        lines.append(
            "\n".join(
                np.char.add(
                    np.char.add(mz.astype("U32"), " "), inten.astype("U32")
                )
            )
        )
    lines.append("END IONS")
    return "\n".join(lines) + "\n\n"


def truncate_tail(path: str | os.PathLike, offset: int) -> bool:
    """Drop output bytes past ``offset`` — the resume repair for a torn
    append (a crash between an MGF append and its checkpoint, or an
    un-fsynced tail a power cut shredded).

    Returns True when the surviving tail ends on a record boundary
    (``END IONS``), which every manifest-recorded offset must: the
    commit protocol only records offsets after whole-record appends, so
    a ragged boundary here means the damage reaches INTO the committed
    prefix and the caller should fall back to a hash check / restart
    rather than trust the truncation alone."""
    path = os.fspath(path)
    with open(path, "r+b") as fh:
        fh.truncate(int(offset))
    if offset <= 0:
        return True
    with open(path, "rb") as fh:
        fh.seek(max(0, int(offset) - 4096))
        tail = fh.read()
    return tail.rstrip().endswith(b"END IONS")


def _write_records(fh: IO[str], spectra) -> int:
    """Stream records into an open text sink; returns the record count.
    The ONE formatting loop all three ``write_mgf`` targets share."""
    n = 0
    for s in spectra:
        fh.write(format_spectrum(s))
        n += 1
    return n


def write_mgf(
    spectra: Sequence[Spectrum] | Iterator[Spectrum],
    path_or_file: str | os.PathLike | IO[str] | None,
    append: bool = False,
) -> str | None:
    """Write spectra to an MGF file, file object, or (path None) a string.

    Streams one record at a time — never materialises the whole file in
    memory.  ``append`` reproduces the reference's ``--append`` output mode
    (ref src/average_spectrum_clustering.py:183-184,198).

    All three targets run under the same traced writer: every branch
    opens a ``write:mgf`` span with an ``n_spectra`` note, so a trace of
    a run that writes through a file object (multi-part shards, tests)
    or builds a string accounts for its write time like the path branch
    always did.
    """
    if path_or_file is None:
        with tracing.span("write:mgf", path=None, append=False) as sp:
            buf = io.StringIO()
            sp.note(n_spectra=_write_records(buf, spectra))
            return buf.getvalue()
    if hasattr(path_or_file, "write"):
        # the caller opened the file: its mode (append vs truncate) is
        # unknowable here, so the label must not claim either
        with tracing.span(
            "write:mgf", path=str(getattr(path_or_file, "name", "<stream>")),
            append=None,
        ) as sp:
            sp.note(n_spectra=_write_records(path_or_file, spectra))
        return None
    mode = "a" if append else "w"
    with tracing.span("write:mgf", path=os.fspath(path_or_file),
                      append=append) as sp:
        with open(os.fspath(path_or_file), mode, encoding="utf-8") as fh:
            sp.note(n_spectra=_write_records(fh, spectra))
    return None
