"""MaRaCluster cluster-assignment TSV ingest.

Format: one ``<raw_file>\t<scan>\t...`` line per spectrum, clusters separated
by blank lines.  Two views are needed by the pipeline:

* ``read_maracluster_clusters`` → list of scan lists, one per cluster
  (ref src/binning.py:33-51 read_cluster_list — note the reference appends a
  cluster only when a blank line follows it, so a file not ending in a blank
  line silently drops the last cluster; we keep a trailing non-empty cluster
  and document the divergence).
* ``scan_to_cluster`` → scan → "cluster-N" mapping with 1-based numbering
  (ref src/convert_mgf_cluster.py:33-44 read_clusters; numbering starts at 1
  and increments on every blank line, reproduced exactly, including the quirk
  that consecutive blank lines skip numbers).
"""

from __future__ import annotations

import os


def read_maracluster_clusters(path: str | os.PathLike) -> list[list[int]]:
    """Parse a MaRaCluster TSV into a list of clusters, each a list of scans."""
    clusters: list[list[int]] = []
    cluster: list[int] = []
    with open(path) as fh:
        for line in fh:
            cols = line.split()
            if not cols:
                clusters.append(cluster)
                cluster = []
                continue
            cluster.append(int(cols[1]))
    if cluster:
        # divergence from ref src/binning.py:33-51: keep a trailing cluster
        # that is not followed by a blank line instead of dropping it
        clusters.append(cluster)
    return clusters


def scan_to_cluster(path: str | os.PathLike, prefix: str = "cluster-") -> dict[int, str]:
    """Map scan number → cluster accession ("cluster-1", ...).

    Reproduces ref src/convert_mgf_cluster.py:33-44: the index starts at 1
    and increments on each blank line.
    """
    mapping: dict[int, str] = {}
    index = 1
    with open(path) as fh:
        for line in fh:
            if not line.strip():
                index += 1
            else:
                cols = line.split("\t")
                mapping[int(cols[1])] = f"{prefix}{index}"
    return mapping
