"""mzML reading, from scratch (stdlib XML + base64/zlib).

Covers the capabilities the reference consumes from three different mzML
libraries:

* iterate MS2 spectra with peaks, precursor m/z/charge, RT and scan number
  (pyteomics ``mzml.read`` at ref src/binning.py:80-118; pymzml at ref
  src/plot_cluster.py:71-86)
* random access by scan number (pyOpenMS ``MzMLFile`` + ``SpectrumLookup``
  regex scan indexing at ref src/convert_mgf_cluster.py:101-118)

Supported encodings: 32/64-bit floats, zlib or no compression — the
combinations standard instruments emit.  Gzip-transparent like the MGF
reader (ref src/binning.py:72-77).
"""

from __future__ import annotations

import base64
import gzip
import os
import re
import struct
import zlib
import xml.etree.ElementTree as ET
from xml.sax.saxutils import quoteattr
from typing import IO, Iterator

import numpy as np

from specpride_tpu.data.peaks import Spectrum
from specpride_tpu.observability import tracing

# mzML controlled-vocabulary accessions
_CV_MS_LEVEL = "MS:1000511"
_CV_SCAN_START_TIME = "MS:1000016"
_CV_SELECTED_MZ = "MS:1000744"
_CV_CHARGE = "MS:1000041"
_CV_MZ_ARRAY = "MS:1000514"
_CV_INTENSITY_ARRAY = "MS:1000515"
_CV_64BIT = "MS:1000523"
_CV_32BIT = "MS:1000521"
_CV_ZLIB = "MS:1000574"

_SCAN_RE = re.compile(r"scan=(\d+)")


def _open_binary(path: str | os.PathLike) -> IO[bytes]:
    path = os.fspath(path)
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _local(tag: str) -> str:
    """Strip the XML namespace."""
    return tag.rpartition("}")[2]


def _decode_binary(text: str, bits: int, compressed: bool) -> np.ndarray:
    raw = base64.b64decode(text)
    if compressed:
        raw = zlib.decompress(raw)
    dtype = np.float64 if bits == 64 else np.float32
    return np.frombuffer(raw, dtype=dtype).astype(np.float64)


def scan_from_id(spectrum_id: str) -> int | None:
    """Scan number from an mzML spectrum id (``... scan=17555``) — the
    capability of pyOpenMS ``SpectrumLookup`` with the default regex
    (ref src/convert_mgf_cluster.py:103-104)."""
    m = _SCAN_RE.search(spectrum_id)
    if m:
        return int(m.group(1))
    # fall back: trailing integer (some converters emit bare numeric ids)
    tail = spectrum_id.rsplit("=", 1)[-1].rsplit(" ", 1)[-1]
    return int(tail) if tail.isdigit() else None


def _parse_spectrum_elem(elem: ET.Element) -> tuple[Spectrum, int, int | None]:
    """One <spectrum> element → (Spectrum, ms_level, scan)."""
    ms_level = 0
    rt = 0.0
    rt_minutes = False
    precursor_mz = 0.0
    charge = 0
    mz = np.zeros((0,), np.float64)
    intensity = np.zeros((0,), np.float64)

    for cv in elem.iter():
        tag = _local(cv.tag)
        if tag == "cvParam":
            acc = cv.get("accession", "")
            if acc == _CV_MS_LEVEL:
                ms_level = int(cv.get("value", "0") or 0)
            elif acc == _CV_SCAN_START_TIME:
                rt = float(cv.get("value", "0") or 0.0)
                rt_minutes = cv.get("unitName", "") == "minute"
            elif acc == _CV_SELECTED_MZ:
                precursor_mz = float(cv.get("value", "0") or 0.0)
            elif acc == _CV_CHARGE:
                charge = int(cv.get("value", "0") or 0)

    for bda in elem.iter():
        if _local(bda.tag) != "binaryDataArray":
            continue
        bits = 64
        compressed = False
        kind = None
        text = ""
        for child in bda.iter():
            tag = _local(child.tag)
            if tag == "cvParam":
                acc = child.get("accession", "")
                if acc == _CV_64BIT:
                    bits = 64
                elif acc == _CV_32BIT:
                    bits = 32
                elif acc == _CV_ZLIB:
                    compressed = True
                elif acc == _CV_MZ_ARRAY:
                    kind = "mz"
                elif acc == _CV_INTENSITY_ARRAY:
                    kind = "intensity"
            elif tag == "binary":
                text = child.text or ""
        if kind == "mz":
            mz = _decode_binary(text, bits, compressed)
        elif kind == "intensity":
            intensity = _decode_binary(text, bits, compressed)

    sid = elem.get("id", "")
    scan = scan_from_id(sid)
    if rt_minutes:
        rt *= 60.0
    spec = Spectrum(
        mz=mz,
        intensity=intensity,
        precursor_mz=precursor_mz,
        precursor_charge=charge,
        rt=rt,
        title=sid,
    )
    return spec, ms_level, scan


def iter_mzml(
    path: str | os.PathLike, ms_level: int | None = 2
) -> Iterator[tuple[int | None, Spectrum]]:
    """Yield (scan, Spectrum) from an mzML file, streaming.

    ``ms_level`` filters (None = all levels); the reference skips non-MS2
    scans with a printed error (ref src/binning.py:104-106) — here they are
    silently filtered, callers count them via ``read_mzml_scans``.
    """
    with _open_binary(path) as fh:
        for _, elem in ET.iterparse(fh, events=("end",)):
            if _local(elem.tag) != "spectrum":
                continue
            spec, level, scan = _parse_spectrum_elem(elem)
            if ms_level is None or level == ms_level:
                yield scan, spec
            elem.clear()


def write_mzml(
    spectra: list[tuple[int, Spectrum, dict]],
    path: str | os.PathLike,
) -> None:
    """Minimal mzML writer: (scan, spectrum, userParams) triples.

    Capability parity with pyOpenMS ``MzMLFile().store`` as used by the
    mzML converter variant (ref src/convert_mgf_cluster.py:120-134), which
    attaches 'Cluster accession' / 'Peptide sequence' metaValues — written
    here as <userParam> entries.  64-bit, zlib-compressed arrays.
    """

    def b64(arr: np.ndarray) -> str:
        return base64.b64encode(
            zlib.compress(np.asarray(arr, np.float64).tobytes())
        ).decode("ascii")

    with open(os.fspath(path), "w", encoding="utf-8") as fh:
        fh.write('<?xml version="1.0" encoding="utf-8"?>\n')
        fh.write('<mzML xmlns="http://psi.hupo.org/ms/mzml" version="1.1.0">\n')
        fh.write(f'  <run id="run"><spectrumList count="{len(spectra)}">\n')
        for index, (scan, s, params) in enumerate(spectra):
            fh.write(
                f'    <spectrum index="{index}" id={quoteattr(f"scan={scan}")} '
                f'defaultArrayLength="{s.n_peaks}">\n'
            )
            fh.write(
                '      <cvParam accession="MS:1000511" name="ms level" value="2"/>\n'
            )
            # userParams carry free text (cluster ids, peptide sequences) —
            # quoteattr so &/</quotes survive a round-trip as valid XML
            for key, value in params.items():
                fh.write(
                    f"      <userParam name={quoteattr(str(key))} "
                    f"value={quoteattr(str(value))}/>\n"
                )
            fh.write(
                '      <precursorList count="1"><precursor><selectedIonList '
                'count="1"><selectedIon>\n'
                f'        <cvParam accession="MS:1000744" name="selected ion '
                f'm/z" value="{s.precursor_mz}"/>\n'
                f'        <cvParam accession="MS:1000041" name="charge state" '
                f'value="{s.precursor_charge}"/>\n'
                "      </selectedIon></selectedIonList></precursor>"
                "</precursorList>\n"
                "      <scanList count=\"1\"><scan>\n"
                f'        <cvParam accession="MS:1000016" name="scan start '
                f'time" value="{s.rt}" unitName="second"/>\n'
                "      </scan></scanList>\n"
            )
            fh.write('      <binaryDataArrayList count="2">\n')
            for acc, name, arr in (
                ("MS:1000514", "m/z array", s.mz),
                ("MS:1000515", "intensity array", s.intensity),
            ):
                fh.write(
                    "        <binaryDataArray>"
                    '<cvParam accession="MS:1000523" name="64-bit float"/>'
                    '<cvParam accession="MS:1000574" name="zlib compression"/>'
                    f'<cvParam accession="{acc}" name="{name}"/>'
                    f"<binary>{b64(arr)}</binary></binaryDataArray>\n"
                )
            fh.write("      </binaryDataArrayList>\n    </spectrum>\n")
        fh.write("  </spectrumList></run>\n</mzML>\n")


def read_mzml_scans(
    path: str | os.PathLike,
    scans: set[int] | None = None,
    ms_level: int | None = 2,
) -> dict[int, Spectrum]:
    """Random access by scan number (one streaming pass, dict-keyed — the
    capability of pyteomics random access at ref src/binning.py:83 and
    pyOpenMS SpectrumLookup at ref src/convert_mgf_cluster.py:103-118,
    without the reference's O(scans × spectra) linear rescan)."""
    out: dict[int, Spectrum] = {}
    with tracing.span("parse:mzml", path=os.fspath(path)) as sp:
        for scan, spec in iter_mzml(path, ms_level):
            if scan is None:
                continue
            if scans is None or scan in scans:
                out[scan] = spec
        sp.note(n_scans=len(out))
    return out
