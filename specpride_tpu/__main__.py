"""``python -m specpride_tpu`` entry point."""
from specpride_tpu.cli import main

raise SystemExit(main())
