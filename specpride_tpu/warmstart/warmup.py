"""AOT bucket-shape warmup: compile every manifest entry before the
first chunk dispatches.

``warm_entries`` drives ``jit(fn).lower(avals).compile()`` for each
shape-manifest entry on a thread pool — XLA compilation releases the
GIL, so variants compile CONCURRENTLY, and each compile either pays the
full XLA bill once (then lands in the persistent cache for every later
process) or loads from the cache in milliseconds.  Per-kernel
hit/miss attribution uses the per-thread ``jax.monitoring`` counters in
``warmstart.cache`` (listeners run on the compiling thread, so
concurrent compiles cannot cross-attribute).

Every outcome is journaled as a ``warmup`` event — per-kernel
compile-vs-cache-hit and seconds — which ``specpride stats`` rolls up
into the ``warmstart:`` line.
"""

from __future__ import annotations

import dataclasses
import os
import time

from specpride_tpu.observability import NullJournal, logger
from specpride_tpu.observability import tracing
from specpride_tpu.warmstart import cache, registry
from specpride_tpu.warmstart.manifest import ShapeEntry


@dataclasses.dataclass
class WarmResult:
    entry: ShapeEntry
    status: str  # "compiled" | "cache_hit" | "skipped" | "error"
    seconds: float
    detail: str = ""

    @property
    def cache_hit(self) -> bool:
        return self.status == "cache_hit"


def _compile_one(item) -> tuple[int, WarmResult]:
    """Pool-worker half: the XLA compile (or persistent-cache load) of
    an already-lowered entry.  ``seconds`` = this entry's own lowering
    time plus its compile time — pool QUEUE WAIT is excluded (with more
    entries than workers it would double-count whole compile rounds
    into every second-wave entry)."""
    i, entry, lowered, lower_s = item
    cache.thread_counts_reset()
    t0 = time.perf_counter()
    try:
        lowered.compile()
    except Exception as e:  # noqa: BLE001 - a bad variant (e.g. Pallas
        # Mosaic-compiling off-TPU) must not abort the rest
        return i, WarmResult(
            entry, "error", lower_s + time.perf_counter() - t0,
            f"{type(e).__name__}: {e}",
        )
    counts = cache.thread_counts()
    hit = counts.get("hits", 0) > 0 and counts.get("misses", 0) == 0
    return i, WarmResult(
        entry, "cache_hit" if hit else "compiled",
        lower_s + time.perf_counter() - t0,
    )


def warm_entries(
    entries: list[ShapeEntry], journal=None, jobs: int = 0,
    donate: bool = True,
) -> list[WarmResult]:
    """Warm every entry — tracing/lowering SEQUENTIAL, XLA compiles
    concurrent; journal one ``warmup`` event per entry and return the
    results (stable entry order).

    The split is load-bearing, not a style choice: jax tracing is where
    the wall-time is NOT (XLA compilation dominates and releases the
    GIL), and lowering the same call concurrently with other traces was
    measured to produce a canonicalization-unstable module — the same
    (kernel, shape-class) hashed to one of TWO persistent-cache keys
    depending on thread interleaving, so a warmup entry could silently
    re-compile instead of hitting the entry its own cold run wrote.
    Sequential lowering is byte-identical to what a dispatch traces, so
    warmup keys always match run keys."""
    journal = journal if journal is not None else NullJournal()
    if not entries:
        return []
    if jobs <= 0:
        try:
            cores = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            cores = os.cpu_count() or 1
        jobs = max(1, min(8, cores, len(entries)))
    import concurrent.futures

    t0 = time.perf_counter()
    results: list[WarmResult | None] = [None] * len(entries)
    with tracing.span("warmup", n_entries=len(entries), jobs=jobs):
        work = []
        for i, entry in enumerate(entries):
            t_start = time.perf_counter()
            try:
                built = registry.build(entry, donate=donate)
            except (ValueError, TypeError) as e:
                results[i] = WarmResult(
                    entry, "skipped", 0.0, f"bad entry: {e}"
                )
                continue
            if built is None:
                results[i] = WarmResult(
                    entry, "skipped", 0.0, "kernel not in warmup registry"
                )
                continue
            fn, avals, statics = built
            try:
                lowered = fn.lower(*avals, **statics)
            except Exception as e:  # noqa: BLE001 - e.g. Pallas off-TPU
                results[i] = WarmResult(
                    entry, "error", time.perf_counter() - t_start,
                    f"{type(e).__name__}: {e}",
                )
                continue
            work.append((i, entry, lowered, time.perf_counter() - t_start))
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=jobs, thread_name_prefix="specpride-warmup"
        ) as pool:
            for i, res in pool.map(_compile_one, work):
                results[i] = res
    for r in results:
        journal.emit(
            "warmup",
            kernel=r.entry.kernel,
            shape_key=list(r.entry.shape_key),
            cache_hit=r.cache_hit,
            seconds=round(r.seconds, 4),
            status=r.status,
            **({"detail": r.detail} if r.detail else {}),
        )
    n_hit = sum(r.cache_hit for r in results)
    n_err = sum(r.status in ("error", "skipped") for r in results)
    logger.info(
        "warmup: %d kernel variant(s) in %.2fs — %d compiled, %d cache "
        "hit(s)%s",
        len(results), time.perf_counter() - t0,
        sum(r.status == "compiled" for r in results), n_hit,
        f", {n_err} skipped/failed" if n_err else "",
    )
    return results
