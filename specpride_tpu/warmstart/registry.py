"""Kernel registry: rebuild a kernel's exact jit call from a manifest
entry, for AOT warmup.

Every device dispatch site in ``backends.tpu_backend`` keys its shape
class (``_note_dispatch``'s ``shape_key``) by EVERY static argument of
the underlying jitted function, so ``(kernel, shape_key[, config])``
fully determines one XLA compilation.  Each builder here reconstructs
the ``ShapeDtypeStruct`` argument list + static kwargs for one kernel —
dtype-exact mirrors of what the dispatch sites ship — so
``jit(fn).lower(*avals, **statics).compile()`` produces the very
executable the run would compile, and the persistent compilation cache
entry it writes is the one the run will load.

Kernels absent from the registry (none today) are skipped by warmup
with a journal note rather than failing the run.  Mesh-sharded
dispatches compile against sharded avals and are NOT reproduced here —
warmup covers the single-host paths (the manifest from a mesh run still
warms the unsharded variants, which is harmless but unused).

Reduced-precision shape classes (--precision): dispatch sites append
string dtype TOKENS to the shape key when a channel ships narrowed —
("bf16"|"int8") for the intensity codes, the m/z channel's actual dtype
("f32"|"bf16" from the pack-time exactness probe), "i16"/"i32" for
narrowed index channels — because input dtype is part of the jit
signature, i.e. a distinct XLA compile.  The builders here parse those
tokens back into dtype-exact avals; keys without tokens rebuild the f32
classes byte-identically to pre-precision manifests.

Buffer donation: ``build(entry, donate=...)`` returns the jitted twin
matching the run's donation setting (donation changes the executable's
aliasing spec, so warming the wrong twin would populate the wrong
persistent-cache entry).
"""

from __future__ import annotations

import jax.numpy as jnp

from specpride_tpu.warmstart.manifest import ShapeEntry

_CONFIG_TYPES = None


def _configs():
    global _CONFIG_TYPES
    if _CONFIG_TYPES is None:
        from specpride_tpu.config import BinMeanConfig, GapAverageConfig

        _CONFIG_TYPES = {
            "BinMeanConfig": BinMeanConfig,
            "GapAverageConfig": GapAverageConfig,
        }
    return _CONFIG_TYPES


def _rebuild_config(config: dict | None):
    if config is None:
        return None
    fields = dict(config)
    type_name = fields.pop("type", None)
    cls = _configs().get(type_name)
    if cls is None:
        raise ValueError(f"unknown config type {type_name!r}")
    return cls(**fields)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _bf16():
    # the ONE bf16 dtype accessor — the registry's rebuilt avals must
    # match the dtypes the dispatch sites actually ship, or warm reruns
    # stop hitting the cache
    from specpride_tpu.ops.quantize import _bf16 as q_bf16

    return q_bf16()


def _split_tokens(shape_key):
    """``(ints, tokens)``: the numeric prefix and the trailing dtype
    tokens a reduced-precision dispatch appended."""
    ints = []
    tokens = []
    for v in shape_key:
        if isinstance(v, str):
            tokens.append(v)
        else:
            ints.append(v)
    return tuple(ints), tuple(tokens)


def _code_dtype(token: str):
    return _bf16() if token == "bf16" else jnp.int8


def _mz_dtype(token: str):
    return _bf16() if token == "bf16" else jnp.float32


def _bin_mean_flat(entry: ShapeEntry, impl: str, donate: bool):
    from specpride_tpu.ops import binning

    n_pad, cap, rcap, lcap = entry.shape_key
    avals = (
        _sds((n_pad,), jnp.float32),  # intensity
        _sds((n_pad,), jnp.int32),  # gbin
        _sds((rcap,), jnp.bool_),  # keep_runs
    )
    statics = dict(total_cap=cap, rcap=rcap, lcap=lcap, impl=impl)
    fn = (
        binning.bin_mean_flat_intensity_donated if donate
        else binning.bin_mean_flat_intensity
    )
    return fn, avals, statics


def _bin_mean_flat_q(entry: ShapeEntry, impl: str, donate: bool):
    from specpride_tpu.ops import binning

    (n_pad, cap, rcap, lcap), tokens = _split_tokens(entry.shape_key)
    prec = tokens[0] if tokens else "bf16"
    avals = (
        _sds((n_pad,), _code_dtype(prec)),  # intensity codes
        _sds((n_pad,), jnp.bool_),  # run_start
        _sds((rcap,), jnp.bool_),  # keep_runs
    )
    statics = dict(total_cap=cap, rcap=rcap, lcap=lcap, impl=impl)
    fn = (
        binning.bin_mean_flat_q_donated if donate
        else binning.bin_mean_flat_q
    )
    return fn, avals, statics


def _bin_mean_bucketized(entry: ShapeEntry, donate: bool):
    from specpride_tpu.ops import binning

    (size, k, cap, lcap), tokens = _split_tokens(entry.shape_key)
    int_dt = _code_dtype(tokens[0]) if tokens else jnp.float32
    mz_dt = _mz_dtype(tokens[1]) if len(tokens) > 1 else jnp.float32
    avals = (
        _sds((size, k), mz_dt),  # mz
        _sds((size, k), int_dt),  # intensity
        _sds((size, k), jnp.int32),  # bins
        _sds((size,), jnp.int32),  # n_members
    )
    statics = dict(
        config=_rebuild_config(entry.config), total_cap=cap, lcap=lcap
    )
    fn = (
        binning.bin_mean_deduped_compact_donated if donate
        else binning.bin_mean_deduped_compact
    )
    return fn, avals, statics


def _gap_average_compact(entry: ShapeEntry, impl: str, donate: bool):
    from specpride_tpu.ops import gap_average as ga

    (size, k, cap), tokens = _split_tokens(entry.shape_key)
    int_dt = _code_dtype(tokens[0]) if tokens else jnp.float32
    mz_dt = _mz_dtype(tokens[1]) if len(tokens) > 1 else jnp.float32
    seg_dt = (
        jnp.int16 if len(tokens) > 2 and tokens[2] == "i16" else jnp.int32
    )
    avals = (
        _sds((size, k), mz_dt),  # mz
        _sds((size, k), int_dt),  # intensity
        _sds((size, k), seg_dt),  # seg
        _sds((size,), jnp.int32),  # n_valid
        _sds((size,), jnp.int32),  # quorum
        _sds((size,), jnp.int32),  # n_members
    )
    statics = dict(
        config=_rebuild_config(entry.config), total_cap=cap, impl=impl
    )
    fn = (
        ga.gap_average_compact_donated if donate else ga.gap_average_compact
    )
    return fn, avals, statics


def _medoid_args(size, k, m, idx_dt):
    return (
        _sds((size, k), idx_dt),  # bins, pre-sorted (bin, member)
        _sds((size, k), idx_dt),  # member_id, padding = m
    ), (
        _sds((size, m), jnp.int32),  # n_peaks
        _sds((size, m), jnp.bool_),  # member_mask
        _sds((size,), jnp.int32),  # n_members
    )


def _medoid_select(entry: ShapeEntry, donate: bool):
    from specpride_tpu.ops import similarity as sim

    (size, k, m, lcap), tokens = _split_tokens(entry.shape_key)
    idx_dt = jnp.int16 if "i16" in tokens else jnp.int32
    core, finalize = _medoid_args(size, k, m, idx_dt)
    fn = (
        sim.medoid_select_packed_donated if donate
        else sim.medoid_select_packed
    )
    return fn, core + finalize, dict(m=m, lcap=lcap)


def _shared_bins(entry: ShapeEntry, donate: bool):
    from specpride_tpu.ops import similarity as sim

    (size, k, m, lcap), tokens = _split_tokens(entry.shape_key)
    idx_dt = jnp.int16 if "i16" in tokens else jnp.int32
    core, _ = _medoid_args(size, k, m, idx_dt)
    fn = (
        sim.shared_bins_packed_donated if donate
        else sim.shared_bins_packed
    )
    return fn, core, dict(m=m, lcap=lcap)


def _cosine_packed(entry: ShapeEntry, donate: bool):
    from specpride_tpu.ops import similarity as sim

    cosine_packed = (
        sim.cosine_packed_donated if donate else sim.cosine_packed
    )

    size, k, pr, m = entry.shape_key
    avals = (
        _sds((size, pr), jnp.int32),  # rep_bins
        _sds((size, pr), jnp.float32),  # rep_int
        _sds((size,), jnp.int32),  # rep_edges
        _sds((size, k), jnp.int32),  # mem_bins
        _sds((size, k), jnp.float32),  # mem_int
        _sds((size, k), jnp.int32),  # mem_member
        _sds((size, m), jnp.int32),  # mem_edges
        _sds((size, m), jnp.bool_),  # member_mask
        _sds((size,), jnp.int32),  # n_members
    )
    return cosine_packed, avals, dict(m=m)


def _cosine_flat(entry: ShapeEntry, donate: bool):
    from specpride_tpu.ops import similarity as sim

    cosine_flat = sim.cosine_flat_donated if donate else sim.cosine_flat

    (
        n_pad, nr_pad, rows_cap, s_pad,
        shift, l_rep, l_row, l_spec, l_mem, l_members,
    ) = entry.shape_key
    avals = (
        _sds((nr_pad,), jnp.int32),  # rkey
        _sds((nr_pad,), jnp.float32),  # rint
        _sds((n_pad,), jnp.int32),  # mkey
        _sds((n_pad,), jnp.float32),  # mint
        _sds((n_pad,), jnp.int32),  # spec_elem
        _sds((n_pad,), jnp.int32),  # pos
        _sds((s_pad + 1,), jnp.int32),  # spec_offsets
        _sds((s_pad,), jnp.int32),  # spec_row
        _sds((s_pad,), jnp.int32),  # npos
        _sds((rows_cap + 1,), jnp.int32),  # rep_offsets
        _sds((rows_cap + 1,), jnp.int32),  # row_spec_offsets
        _sds((rows_cap,), jnp.int32),  # n_members
    )
    statics = dict(
        shift=shift, l_rep=l_rep, l_row=l_row, l_spec=l_spec,
        l_mem=l_mem, l_members=l_members,
    )
    return cosine_flat, avals, statics


_BUILDERS = {
    "bin_mean_flat_intensity": lambda e, d: _bin_mean_flat(e, "scan", d),
    "bin_mean_flat_intensity_pallas": lambda e, d: _bin_mean_flat(
        e, "pallas", d
    ),
    "bin_mean_flat_q": lambda e, d: _bin_mean_flat_q(e, "scan", d),
    "bin_mean_flat_q_pallas": lambda e, d: _bin_mean_flat_q(
        e, "pallas", d
    ),
    "bin_mean_bucketized": _bin_mean_bucketized,
    "gap_average_compact": lambda e, d: _gap_average_compact(
        e, "scan", d
    ),
    "gap_average_compact_pallas": lambda e, d: _gap_average_compact(
        e, "pallas", d
    ),
    "medoid_select_packed": _medoid_select,
    "shared_bins_packed": _shared_bins,
    "cosine_packed": _cosine_packed,
    "cosine_flat": _cosine_flat,
}


def known_kernels() -> tuple[str, ...]:
    return tuple(sorted(_BUILDERS))


def build(entry: ShapeEntry, donate: bool = True):
    """``(jitted_fn, avals, static_kwargs)`` for a manifest entry, or
    None for a kernel this registry cannot rebuild.  ``donate`` selects
    the jit twin matching the run's donation setting (the backend
    default; ``--no-donate`` runs warm the plain twin)."""
    builder = _BUILDERS.get(entry.kernel)
    if builder is None:
        return None
    return builder(entry, donate)
