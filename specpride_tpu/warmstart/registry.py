"""Kernel registry: rebuild a kernel's exact jit call from a manifest
entry, for AOT warmup.

Every device dispatch site in ``backends.tpu_backend`` keys its shape
class (``_note_dispatch``'s ``shape_key``) by EVERY static argument of
the underlying jitted function, so ``(kernel, shape_key[, config])``
fully determines one XLA compilation.  Each builder here reconstructs
the ``ShapeDtypeStruct`` argument list + static kwargs for one kernel —
dtype-exact mirrors of what the dispatch sites ship — so
``jit(fn).lower(*avals, **statics).compile()`` produces the very
executable the run would compile, and the persistent compilation cache
entry it writes is the one the run will load.

Kernels absent from the registry (none today) are skipped by warmup
with a journal note rather than failing the run.  Mesh-sharded
dispatches compile against sharded avals and are NOT reproduced here —
warmup covers the single-host paths (the manifest from a mesh run still
warms the unsharded variants, which is harmless but unused).
"""

from __future__ import annotations

import jax.numpy as jnp

from specpride_tpu.warmstart.manifest import ShapeEntry

_CONFIG_TYPES = None


def _configs():
    global _CONFIG_TYPES
    if _CONFIG_TYPES is None:
        from specpride_tpu.config import BinMeanConfig, GapAverageConfig

        _CONFIG_TYPES = {
            "BinMeanConfig": BinMeanConfig,
            "GapAverageConfig": GapAverageConfig,
        }
    return _CONFIG_TYPES


def _rebuild_config(config: dict | None):
    if config is None:
        return None
    fields = dict(config)
    type_name = fields.pop("type", None)
    cls = _configs().get(type_name)
    if cls is None:
        raise ValueError(f"unknown config type {type_name!r}")
    return cls(**fields)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _bin_mean_flat(entry: ShapeEntry, impl: str):
    from specpride_tpu.ops.binning import bin_mean_flat_intensity

    n_pad, cap, rcap, lcap = entry.shape_key
    avals = (
        _sds((n_pad,), jnp.float32),  # intensity
        _sds((n_pad,), jnp.int32),  # gbin
        _sds((rcap,), jnp.bool_),  # keep_runs
    )
    statics = dict(total_cap=cap, rcap=rcap, lcap=lcap, impl=impl)
    return bin_mean_flat_intensity, avals, statics


def _bin_mean_bucketized(entry: ShapeEntry):
    from specpride_tpu.ops.binning import bin_mean_deduped_compact

    size, k, cap, lcap = entry.shape_key
    avals = (
        _sds((size, k), jnp.float32),  # mz
        _sds((size, k), jnp.float32),  # intensity
        _sds((size, k), jnp.int32),  # bins
        _sds((size,), jnp.int32),  # n_members
    )
    statics = dict(
        config=_rebuild_config(entry.config), total_cap=cap, lcap=lcap
    )
    return bin_mean_deduped_compact, avals, statics


def _gap_average_compact(entry: ShapeEntry, impl: str):
    from specpride_tpu.ops.gap_average import gap_average_compact

    size, k, cap = entry.shape_key
    avals = (
        _sds((size, k), jnp.float32),  # mz
        _sds((size, k), jnp.float32),  # intensity
        _sds((size, k), jnp.int32),  # seg
        _sds((size,), jnp.int32),  # n_valid
        _sds((size,), jnp.int32),  # quorum
        _sds((size,), jnp.int32),  # n_members
    )
    statics = dict(
        config=_rebuild_config(entry.config), total_cap=cap, impl=impl
    )
    return gap_average_compact, avals, statics


def _medoid_args(size, k, m):
    return (
        _sds((size, k), jnp.int32),  # bins, pre-sorted (bin, member)
        _sds((size, k), jnp.int32),  # member_id, padding = m
    ), (
        _sds((size, m), jnp.int32),  # n_peaks
        _sds((size, m), jnp.bool_),  # member_mask
        _sds((size,), jnp.int32),  # n_members
    )


def _medoid_select(entry: ShapeEntry):
    from specpride_tpu.ops.similarity import medoid_select_packed

    size, k, m, lcap = entry.shape_key
    core, finalize = _medoid_args(size, k, m)
    return medoid_select_packed, core + finalize, dict(m=m, lcap=lcap)


def _shared_bins(entry: ShapeEntry):
    from specpride_tpu.ops.similarity import shared_bins_packed

    size, k, m, lcap = entry.shape_key
    core, _ = _medoid_args(size, k, m)
    return shared_bins_packed, core, dict(m=m, lcap=lcap)


def _cosine_packed(entry: ShapeEntry):
    from specpride_tpu.ops.similarity import cosine_packed

    size, k, pr, m = entry.shape_key
    avals = (
        _sds((size, pr), jnp.int32),  # rep_bins
        _sds((size, pr), jnp.float32),  # rep_int
        _sds((size,), jnp.int32),  # rep_edges
        _sds((size, k), jnp.int32),  # mem_bins
        _sds((size, k), jnp.float32),  # mem_int
        _sds((size, k), jnp.int32),  # mem_member
        _sds((size, m), jnp.int32),  # mem_edges
        _sds((size, m), jnp.bool_),  # member_mask
        _sds((size,), jnp.int32),  # n_members
    )
    return cosine_packed, avals, dict(m=m)


def _cosine_flat(entry: ShapeEntry):
    from specpride_tpu.ops.similarity import cosine_flat

    (
        n_pad, nr_pad, rows_cap, s_pad,
        shift, l_rep, l_row, l_spec, l_mem, l_members,
    ) = entry.shape_key
    avals = (
        _sds((nr_pad,), jnp.int32),  # rkey
        _sds((nr_pad,), jnp.float32),  # rint
        _sds((n_pad,), jnp.int32),  # mkey
        _sds((n_pad,), jnp.float32),  # mint
        _sds((n_pad,), jnp.int32),  # spec_elem
        _sds((n_pad,), jnp.int32),  # pos
        _sds((s_pad + 1,), jnp.int32),  # spec_offsets
        _sds((s_pad,), jnp.int32),  # spec_row
        _sds((s_pad,), jnp.int32),  # npos
        _sds((rows_cap + 1,), jnp.int32),  # rep_offsets
        _sds((rows_cap + 1,), jnp.int32),  # row_spec_offsets
        _sds((rows_cap,), jnp.int32),  # n_members
    )
    statics = dict(
        shift=shift, l_rep=l_rep, l_row=l_row, l_spec=l_spec,
        l_mem=l_mem, l_members=l_members,
    )
    return cosine_flat, avals, statics


_BUILDERS = {
    "bin_mean_flat_intensity": lambda e: _bin_mean_flat(e, "scan"),
    "bin_mean_flat_intensity_pallas": lambda e: _bin_mean_flat(e, "pallas"),
    "bin_mean_bucketized": _bin_mean_bucketized,
    "gap_average_compact": lambda e: _gap_average_compact(e, "scan"),
    "gap_average_compact_pallas": lambda e: _gap_average_compact(
        e, "pallas"
    ),
    "medoid_select_packed": _medoid_select,
    "shared_bins_packed": _shared_bins,
    "cosine_packed": _cosine_packed,
    "cosine_flat": _cosine_flat,
}


def known_kernels() -> tuple[str, ...]:
    return tuple(sorted(_BUILDERS))


def build(entry: ShapeEntry):
    """``(jitted_fn, avals, static_kwargs)`` for a manifest entry, or
    None for a kernel this registry cannot rebuild."""
    builder = _BUILDERS.get(entry.kernel)
    if builder is None:
        return None
    return builder(entry)
