"""Measured kernel routing: one table for every per-(method, platform)
execution-path decision the device backend makes.

Generalizes the one-off CPU-only gap-average reroute (PR 4): instead of
an inline ``_cpu_only_devices()`` check, the backend asks this table
which path carries a method's heavy reduction on the current platform:

* ``host-vectorized`` — the exact-f64 vectorized host consensus (the
  measured winner for gap-average on CPU-only jax: the device path ran
  at 0.29x of it, BENCH_r08);
* ``xla`` — the XLA ``ops.segments`` seg-scan kernels (the log2(lcap)
  Hillis-Steele formulation);
* ``pallas`` — the fused single-pass Pallas kernels
  (``ops.pallas_kernels.seg_mean_pallas``), selectable only where
  Pallas lowers (the backend falls back to ``xla`` and journals the
  fallback otherwise).

Decisions are seeded from measured static defaults and optionally
overridden by a bench-derived file (``--routing-table FILE`` or the
``SPECPRIDE_ROUTING`` env var; ``bench.py``'s ``pallas_ab`` section
emits one), so a platform where the Pallas kernel wins its A/B can
promote it without a code change — and the promotion is visible:
every decision the backend acts on is journaled as the existing
``routing`` event.  ``--force-device`` remains the escape hatch that
pins the requested device kernels.

Override file format:

    {"version": 1, "entries": [
      {"method": "gap-average", "platform": "tpu",
       "path": "pallas", "reason": "pallas_ab r10: 1.8x over seg_scan"}]}
"""

from __future__ import annotations

import dataclasses
import json
import os

PATHS = ("host-vectorized", "xla", "pallas")

# measured static defaults; ("*" platform) rows are the fallback.
# gap-average/cpu pins the BENCH_r08 decision: no accelerator to win on
# and the CPU 'device' kernel measured 0.29x of the host consensus.
_STATIC: dict[tuple[str, str], tuple[str, str]] = {
    ("gap-average", "cpu"): ("host-vectorized", "cpu-only-devices"),
    ("gap-average", "*"): ("xla", "static-default"),
    ("bin-mean", "*"): ("xla", "static-default"),
    ("medoid", "*"): ("xla", "static-default"),
}


@dataclasses.dataclass(frozen=True)
class Decision:
    path: str  # one of PATHS
    reason: str
    source: str  # "static" | "override"


class RoutingTable:
    """Static defaults + optional override file, queried per decision."""

    def __init__(self, overrides: dict[tuple[str, str], tuple[str, str]]
                 | None = None, origin: str | None = None):
        self._overrides = dict(overrides or {})
        self.origin = origin  # override file path, for logs

    @classmethod
    def load(cls, path: str | None = None) -> "RoutingTable":
        """Table with overrides from ``path`` (or ``SPECPRIDE_ROUTING``
        when unset; no file -> pure static defaults).  A malformed or
        missing EXPLICIT file raises — a typo'd override must not
        silently fall back to defaults."""
        explicit = path is not None
        path = path or os.environ.get("SPECPRIDE_ROUTING") or None
        if not path:
            return cls()
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
            entries = doc["entries"] if isinstance(doc, dict) else None
            if doc.get("version") != 1 or not isinstance(entries, list):
                raise ValueError("not a v1 routing-override file")
            overrides = {}
            for e in entries:
                p = e["path"]
                if p not in PATHS:
                    raise ValueError(f"unknown path {p!r} (want {PATHS})")
                overrides[(e["method"], e["platform"])] = (
                    p, str(e.get("reason", "override"))
                )
        except (OSError, ValueError, KeyError, TypeError) as err:
            if explicit:
                raise SystemExit(f"bad routing table {path}: {err}")
            from specpride_tpu.observability import logger

            logger.warning(
                "ignoring SPECPRIDE_ROUTING=%s (%s)", path, err
            )
            return cls()
        return cls(overrides, origin=path)

    def decide(self, method: str, platform: str) -> Decision:
        for key in ((method, platform), (method, "*")):
            if key in self._overrides:
                path, reason = self._overrides[key]
                return Decision(path, reason, "override")
        for key in ((method, platform), (method, "*")):
            if key in _STATIC:
                path, reason = _STATIC[key]
                return Decision(path, reason, "static")
        return Decision("xla", "no-table-entry", "static")


def write_overrides(path: str, entries: list[dict]) -> None:
    """Write a bench-derived override file (``bench.py`` pallas_ab)."""
    for e in entries:
        if e.get("path") not in PATHS:
            raise ValueError(f"unknown path in override entry: {e}")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=1)
        fh.write("\n")
