"""Persistent-compilation-cache control and accounting.

One process-wide state machine replaces the once-per-process env-var
resolution that used to live in ``backends.tpu_backend``: the CLI's
``--compile-cache DIR|off`` configures it explicitly, the backend's
constructor falls back to the default resolution (explicit
``JAX_COMPILATION_CACHE_DIR`` / already-configured jax / the
``SPECPRIDE_JAX_CACHE`` env var / a per-platform dir under
``~/.cache``), and the RESOLUTION IS RECORDED — ``cache_state()``
returns the dir (or the reason the cache stayed off) so the run journal
can tell cached runs from cold ones (the old wiring left no trace,
which made post-mortems guess).

Accounting: ``jax.monitoring`` listeners count persistent-cache hits,
misses and compile-seconds-saved, process-wide and per-thread (the
listeners run on the compiling thread, so the warmup pool can attribute
a hit/miss to the kernel it just compiled even with compiles in
flight concurrently on other workers).
"""

from __future__ import annotations

import dataclasses
import os
import threading

from specpride_tpu.observability import logger

_lock = threading.Lock()
_state: "CacheState | None" = None
_listeners_installed = False

# process-wide persistent-cache counters (mutated by jax.monitoring
# listeners under the GIL; plain ints are fine)
_counts = {"hits": 0, "misses": 0, "requests": 0, "saved_s": 0.0}
_tls = threading.local()


@dataclasses.dataclass(frozen=True)
class CacheState:
    """How the persistent compilation cache was resolved this process."""

    enabled: bool
    dir: str | None
    reason: str  # why it is on/off, e.g. "flag", "env:SPECPRIDE_JAX_CACHE"
    source: str  # "flag" | "env" | "jax-config" | "default" | "off"


def _install_listeners_locked() -> None:
    """Caller holds ``_lock`` — an unguarded check-then-set here could
    register the jax.monitoring listeners twice when worker lanes build
    their resident backends concurrently, double-counting every event."""
    global _listeners_installed
    if _listeners_installed:
        return
    _listeners_installed = True
    try:
        from jax._src import monitoring
    except ImportError:  # pragma: no cover - jax always ships monitoring
        return

    def _on_event(name: str, **kw) -> None:
        if name == "/jax/compilation_cache/cache_hits":
            _counts["hits"] += 1
            _thread_totals()["hits"] += 1
            _bump_tls("hits")
        elif name == "/jax/compilation_cache/cache_misses":
            _counts["misses"] += 1
            _thread_totals()["misses"] += 1
            _bump_tls("misses")
        elif name == "/jax/compilation_cache/compile_requests_use_cache":
            _counts["requests"] += 1
            _thread_totals()["requests"] += 1
            _bump_tls("requests")

    def _on_duration(name: str, secs: float, **kw) -> None:
        if name == "/jax/compilation_cache/compile_time_saved_sec":
            _counts["saved_s"] += max(float(secs), 0.0)
            _thread_totals()["saved_s"] += max(float(secs), 0.0)

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)


def _bump_tls(key: str) -> None:
    counts = getattr(_tls, "counts", None)
    if counts is not None:
        counts[key] = counts.get(key, 0) + 1


def _thread_totals() -> dict:
    """Always-on per-thread totals (the listeners run on the compiling
    thread).  A serving worker lane attributes a job's compile traffic
    by diffing THIS thread's totals — the process-wide ``_counts`` would
    cross-attribute between jobs compiling on concurrent lanes."""
    totals = getattr(_tls, "totals", None)
    if totals is None:
        totals = _tls.totals = {
            "hits": 0, "misses": 0, "requests": 0, "saved_s": 0.0,
        }
    return totals


def thread_counters_snapshot() -> dict:
    """The CURRENT thread's persistent-cache counters (monotone within
    the thread's lifetime) — the per-lane analogue of
    :func:`counters_snapshot`."""
    t = _thread_totals()
    return {
        "hits": t["hits"],
        "misses": t["misses"],
        "requests": t["requests"],
        "saved_s": round(t["saved_s"], 4),
    }


def thread_counters_delta(since: dict) -> dict:
    now = thread_counters_snapshot()
    return {
        k: round(now[k] - since.get(k, 0), 4) if k == "saved_s"
        else now[k] - since.get(k, 0)
        for k in now
    }


def thread_counts_reset() -> None:
    """Arm per-thread hit/miss attribution for the CURRENT thread (the
    warmup pool calls this before each AOT compile)."""
    _tls.counts = {}


def thread_counts() -> dict:
    return dict(getattr(_tls, "counts", None) or {})


def counters_snapshot() -> dict:
    """Process-wide persistent-cache counters (monotone)."""
    return {
        "hits": _counts["hits"],
        "misses": _counts["misses"],
        "requests": _counts["requests"],
        "saved_s": round(_counts["saved_s"], 4),
    }


def counters_delta(since: dict) -> dict:
    now = counters_snapshot()
    return {
        k: round(now[k] - since.get(k, 0), 4) if k == "saved_s"
        else now[k] - since.get(k, 0)
        for k in now
    }


def cache_state() -> CacheState:
    """The resolved cache configuration (resolving with defaults if no
    explicit ``configure_compile_cache`` ran yet)."""
    ensure_default_compile_cache()
    assert _state is not None
    return _state


def configure_compile_cache(spec: str | None) -> CacheState:
    """Resolve and apply the compilation-cache configuration.

    ``spec``: an explicit directory, ``"off"``, or ``None`` for the
    default resolution.  Explicit specs override an earlier default
    resolution (the CLI flag runs before the backend constructor, but
    in-process test/bench sequences may interleave); the default
    resolution runs once and then sticks.

    An EXPLICIT directory also drops
    ``jax_persistent_cache_min_compile_time_secs`` to 0 so every
    compile is cached — the caller asked for cold-start elimination,
    and the warm-rerun "zero fresh compiles" guarantee needs the fast
    compiles cached too.
    """
    global _state
    with _lock:
        _install_listeners_locked()
        if spec is None:
            if _state is None:
                _state = _resolve_default()
            return _state
        if spec == "off":
            _state = CacheState(False, None, "disabled by --compile-cache off",
                                "off")
            _apply(None, None)
            return _state
        path = os.path.abspath(os.path.expanduser(spec))
        if _apply(path, 0.0):
            _state = CacheState(
                True, path, "explicit --compile-cache", "flag"
            )
        else:
            # the journal must not claim a cache that jax never got
            # (unwritable dir, too-old jax): record WHY it is off
            _state = CacheState(
                False, None,
                f"--compile-cache {path} unusable (unwritable or jax "
                "too old)", "flag",
            )
        return _state


def ensure_default_compile_cache() -> CacheState:
    """The backend-constructor entry point: default resolution, once."""
    return configure_compile_cache(None)


def _resolve_default() -> CacheState:
    """The historical resolution order (see the module docstring)."""
    import jax

    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return CacheState(
            True, os.environ["JAX_COMPILATION_CACHE_DIR"],
            "JAX_COMPILATION_CACHE_DIR set", "env",
        )
    try:
        if jax.config.jax_compilation_cache_dir:
            return CacheState(
                True, jax.config.jax_compilation_cache_dir,
                "jax already configured", "jax-config",
            )
    except AttributeError:
        pass  # older jax without the attribute: treat as not configured
    path = os.environ.get("SPECPRIDE_JAX_CACHE")
    if path == "":
        return CacheState(False, None, "SPECPRIDE_JAX_CACHE empty", "env")
    source = "env" if path is not None else "default"
    if path is None:
        # partition by platform: CPU AOT entries compiled inside a
        # TPU-plugin process carry different machine-feature flags than a
        # plain CPU process, and loading a mismatched entry risks SIGILL
        try:
            plat = jax.config.jax_platforms or os.environ.get(
                "JAX_PLATFORMS", ""
            )
        except AttributeError:
            plat = os.environ.get("JAX_PLATFORMS", "")
        path = os.path.join(
            os.path.expanduser("~"), ".cache", "specpride_tpu",
            f"jax_cache_{plat or 'default'}",
        )
    # cache even fast compiles beyond 0.2s: the tunnel round-trips during
    # tracing make every avoided compile worth it
    if _apply(path, 0.2):
        return CacheState(True, path, "default location", source)
    return CacheState(False, None, "cache dir unwritable or jax too old",
                      source)


def _apply(path: str | None, min_secs: float | None) -> bool:
    import jax

    try:
        if path is not None:
            os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        if min_secs is not None:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", min_secs
            )
    except (OSError, AttributeError) as e:
        logger.warning("compilation cache unavailable (%s); running "
                       "uncached", e)
        return False
    # jax memoizes its cache decision + file handle once per process —
    # a compile that ran BEFORE this configuration (imports, another
    # backend, a test earlier in the process) would otherwise pin the
    # cache off/elsewhere forever.  reset_cache() drops the memo so the
    # new directory takes effect from the next compile on.
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # noqa: BLE001 - private API; absent on older jax
        pass  # the config update alone has to do
    return True
