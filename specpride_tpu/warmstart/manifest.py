"""Shape manifests: the persisted record of every (kernel, shape-class)
a workload compiles.

The backend already tracks dispatched shape classes exactly
(``TpuBackend._seen_shapes`` — the same set that drives the journal's
``compile`` events), and kernel shapes are bounded to a few size classes
precisely so compiled programs can be reused.  A manifest freezes that
knowledge to disk so a LATER process can AOT-compile every variant
before its first chunk (``specpride warmup`` / ``--warmup``), turning
the persistent compilation cache from "warm after the first run" into
"warm before the first dispatch".

Format (JSON, versioned, additive):

    {"version": 1,
     "entries": [
       {"kernel": "gap_average_compact", "shape_key": [64, 2048, 1536],
        "config": {"type": "GapAverageConfig", "mz_accuracy": 0.01, ...}},
       {"kernel": "bin_mean_flat_intensity",
        "shape_key": [262144, 1536, 1536, 8], "config": null}]}

``config`` is present only for kernels whose compilation is keyed by a
static method-config dataclass (``CONFIG_KERNELS``); everything else a
kernel needs is in ``shape_key`` (the dispatch sites key their classes
by every static argument for exactly this reason).
"""

from __future__ import annotations

import dataclasses
import json
import os

MANIFEST_VERSION = 1

# default manifest filename inside a --compile-cache dir (the natural
# home: the manifest indexes what the cache beside it holds)
DEFAULT_BASENAME = "shape_manifest.json"

# kernels whose jit signature takes a static method-config dataclass
CONFIG_KERNELS = {
    "bin_mean_bucketized": "BinMeanConfig",
    "gap_average_compact": "GapAverageConfig",
    "gap_average_compact_pallas": "GapAverageConfig",
}


@dataclasses.dataclass(frozen=True)
class ShapeEntry:
    kernel: str
    shape_key: tuple
    config: dict | None = None  # {"type": <dataclass name>, **fields}

    def identity(self) -> tuple:
        return (
            self.kernel,
            tuple(self.shape_key),
            json.dumps(self.config, sort_keys=True),
        )

    def to_json(self) -> dict:
        return {
            "kernel": self.kernel,
            "shape_key": list(self.shape_key),
            "config": self.config,
        }


def config_dict(config_obj) -> dict:
    return {
        "type": type(config_obj).__name__,
        **dataclasses.asdict(config_obj),
    }


def entries_from_seen(
    seen_shapes, method_config=None
) -> list[ShapeEntry]:
    """Manifest entries from a backend's ``_seen_shapes`` set (tuples of
    ``(kernel, *shape_key)``).  ``method_config`` is the run's method
    config object — attached to the kernels that compile against it."""
    cfg = config_dict(method_config) if method_config is not None else None
    out = []
    for key in sorted(seen_shapes, key=lambda t: (t[0], t[1:])):
        kernel, shape_key = key[0], tuple(key[1:])
        want = CONFIG_KERNELS.get(kernel)
        entry_cfg = (
            cfg if want is not None and cfg is not None
            and cfg.get("type") == want else None
        )
        if want is not None and entry_cfg is None:
            # a config-keyed kernel without its config cannot be rebuilt;
            # skip rather than record an unwarmable entry
            continue
        out.append(ShapeEntry(kernel, shape_key, entry_cfg))
    return out


def load_manifest(path: str) -> list[ShapeEntry]:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "entries" not in doc:
        raise ValueError(f"{path}: not a shape manifest")
    version = doc.get("version")
    if version != MANIFEST_VERSION:
        raise ValueError(
            f"{path}: unsupported manifest version {version!r}"
        )
    out = []
    for i, e in enumerate(doc["entries"]):
        try:
            out.append(
                ShapeEntry(
                    str(e["kernel"]), tuple(e["shape_key"]),
                    e.get("config"),
                )
            )
        except (KeyError, TypeError) as err:
            raise ValueError(f"{path}: bad entry #{i}: {err}") from err
    return out


def merge_manifest(path: str, entries: list[ShapeEntry]) -> int:
    """Union ``entries`` into the manifest at ``path`` (created if
    absent), atomically.  Returns the total entry count after the merge.
    Identity is (kernel, shape_key, config) — re-running the same
    workload leaves the manifest unchanged.

    The read-modify-write runs under an ``flock`` on ``path + ".lock"``:
    concurrent finishers sharing one compile-cache dir (multi-host
    ranks, parallel CLI runs) would otherwise each union only their own
    entries and the last ``os.replace`` would drop the others' shape
    classes — exactly the classes a later warmup needs."""
    lock_path = path + ".lock"
    lock_fh = None
    try:
        try:
            import fcntl

            lock_fh = open(lock_path, "a")
            fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
        except (ImportError, OSError):
            lock_fh = None  # non-POSIX / unwritable: best-effort merge
        have: dict[tuple, ShapeEntry] = {}
        if os.path.exists(path):
            for e in load_manifest(path):
                have[e.identity()] = e
        for e in entries:
            have.setdefault(e.identity(), e)
        doc = {
            "version": MANIFEST_VERSION,
            "entries": [e.to_json() for _, e in sorted(have.items())],
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, path)
        return len(have)
    finally:
        if lock_fh is not None:
            lock_fh.close()  # closing drops the flock
