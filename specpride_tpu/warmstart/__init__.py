"""Warm-start subsystem: cold-start elimination for the kernel layer.

Three coupled pieces (ROADMAP item 5, the substrate the item-1 daemon's
"warm kernels" build on):

* ``cache`` — explicit control of JAX's persistent compilation cache
  (``--compile-cache DIR|off`` replacing the env-var-only wiring), plus
  per-process hit/miss/saved-seconds accounting via ``jax.monitoring``
  so a run journal can tell a cached run from a cold one.
* ``manifest`` + ``registry`` + ``warmup`` — a shape manifest persists
  every (kernel, shape-class) a workload compiles; ``specpride warmup``
  (and ``--warmup`` on consensus/select) AOT-compiles them all
  concurrently (``jit(...).lower().compile()``) before the pack lane
  starts, so steady-state runs pay zero XLA compiles.
* ``routing`` — the per-(method, platform) kernel routing table
  (host-vectorized / XLA seg-scan / Pallas), seeded from measured static
  defaults plus an optional bench-derived override file; every decision
  is journaled as the existing ``routing`` event.
"""

from specpride_tpu.warmstart.cache import (  # noqa: F401
    cache_state,
    configure_compile_cache,
    counters_delta,
    counters_snapshot,
    ensure_default_compile_cache,
)
from specpride_tpu.warmstart.manifest import (  # noqa: F401
    ShapeEntry,
    entries_from_seen,
    load_manifest,
    merge_manifest,
)
from specpride_tpu.warmstart.routing import (  # noqa: F401
    Decision,
    RoutingTable,
)
from specpride_tpu.warmstart.warmup import warm_entries  # noqa: F401
