"""Quality evaluation of representative spectra (C5, ref src/benchmark.py).

Two metrics, per cluster:

* mean binned cosine of the representative to the cluster members
  (ref src/benchmark.py:31-38) — numpy oracle or batched device kernel;
* fraction of the representative's ion current explained by b/y fragments
  of the identified peptide (ref src/benchmark.py:40-61) — host-side
  (fragment theory is tiny; ref's version contains an undefined-variable
  bug we do not reproduce, see ops.fragments.fraction_of_by).

The peptide is taken from the representative's USI interpretation suffix
(``...:PEPTIDE/z``) when present.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Sequence

import numpy as np

from specpride_tpu.config import CosineConfig, FragmentConfig
from specpride_tpu.data.peaks import Cluster, Spectrum, peptide_from_usi
from specpride_tpu.ops.fragments import fraction_of_by_batch


@dataclasses.dataclass
class ClusterQuality:
    cluster_id: str
    n_members: int
    n_peaks: int
    avg_cosine: float
    by_fraction: float | None  # None when no peptide is known

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def evaluate(
    representatives: Sequence[Spectrum],
    clusters: Sequence[Cluster],
    backend="tpu",
    cosine_config: CosineConfig = CosineConfig(),
    fragment_config: FragmentConfig = FragmentConfig(),
) -> list[ClusterQuality]:
    """Score each representative against its cluster.

    ``backend``: "numpy", "tpu", or a constructed ``TpuBackend`` (the CLI
    passes one so --mesh/--layout take effect here too)."""
    if len(representatives) != len(clusters):
        raise ValueError("representatives and clusters must align")

    if backend == "numpy":
        from specpride_tpu.backends import numpy_backend as nb

        cosines = np.array(
            [
                nb.average_cosine(r, c.members, cosine_config)
                for r, c in zip(representatives, clusters)
            ]
        )
    else:
        if backend == "tpu":
            from specpride_tpu.backends.tpu_backend import TpuBackend

            backend = TpuBackend()
        cosines = backend.average_cosines(
            list(representatives), list(clusters), cosine_config
        )

    peptides: list[str | None] = []
    for rep, cluster in zip(representatives, clusters):
        peptide = None
        for s in [rep, *cluster.members]:
            pep, _ = peptide_from_usi(s.usi)
            if pep:
                peptide = pep
                break
        peptides.append(peptide)
    # one fragment-table build per unique peptide/charge, not per cluster
    # (ops.fragments.fraction_of_by_batch); NaN = no peptide -> None
    fracs = fraction_of_by_batch(
        peptides,
        np.array([r.precursor_mz for r in representatives]),
        np.array([r.precursor_charge for r in representatives]),
        [r.mz for r in representatives],
        [r.intensity for r in representatives],
        tol=fragment_config.tol,
        tol_mode=fragment_config.tol_mode,
        min_mz=fragment_config.min_mz,
        max_mz=fragment_config.max_mz,
    )
    return [
        ClusterQuality(
            cluster_id=cluster.cluster_id,
            n_members=cluster.n_members,
            n_peaks=rep.n_peaks,
            avg_cosine=float(cos),
            by_fraction=None if np.isnan(frac) else float(frac),
        )
        for rep, cluster, cos, frac in zip(
            representatives, clusters, cosines, fracs
        )
    ]


def summarize(results: Sequence[ClusterQuality]) -> dict:
    """Aggregate metrics across clusters (the numbers the reference prints
    one at a time in its __main__ self-test, ref src/benchmark.py:63-80)."""
    cosines = [r.avg_cosine for r in results]
    fracs = [r.by_fraction for r in results if r.by_fraction is not None]
    return {
        "n_clusters": len(results),
        "mean_cosine": float(np.mean(cosines)) if cosines else 0.0,
        "median_cosine": float(np.median(cosines)) if cosines else 0.0,
        "mean_by_fraction": float(np.mean(fracs)) if fracs else None,
        "n_with_peptide": len(fracs),
    }


def write_report(
    results: Sequence[ClusterQuality], path: str, fmt: str = "json"
) -> None:
    """JSON or CSV report."""
    if fmt == "json":
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(
                {
                    "summary": summarize(results),
                    "clusters": [r.to_dict() for r in results],
                },
                fh,
                indent=1,
            )
    elif fmt == "csv":
        import csv

        with open(path, "w", encoding="utf-8", newline="") as fh:
            # quotes ids containing commas/quotes; LF terminator (csv's
            # default CRLF would make every report diff against older
            # LF-only output and confuse line-oriented tools)
            w = csv.writer(fh, lineterminator="\n")
            w.writerow(
                ["cluster_id", "n_members", "n_peaks", "avg_cosine",
                 "by_fraction"]
            )
            for r in results:
                frac = "" if r.by_fraction is None else f"{r.by_fraction:.6f}"
                w.writerow(
                    [r.cluster_id, r.n_members, r.n_peaks,
                     f"{r.avg_cosine:.6f}", frac]
                )
    else:
        raise ValueError(f"unknown report format {fmt!r}")
