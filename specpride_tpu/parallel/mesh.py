"""Cluster-axis device mesh and sharding helpers.

Design (TPU-first, survey §2): per-cluster kernels are independent, so the
entire framework parallelises over ONE mesh axis — ``"clusters"`` — laid out
over all local+remote devices.  Inputs are sharded along their leading axis
with ``NamedSharding(mesh, P("clusters", None, ...))``; the jitted vmapped
kernels then SPMD-partition with no collectives in the hot loop (XLA inserts
only the final all-gather when the host fetches results).

Multi-host: ``initialize_distributed`` wraps ``jax.distributed.initialize``
for rank discovery; each process then builds ``cluster_mesh(jax.local_
devices())`` over its OWN chips and runs its block of clusters — clusters
are independent, so no collective ever crosses hosts, and a pod-global
mesh would force every process to ``device_put`` identical global arrays
(jax asserts exactly that), which block-sharded inputs violate by design
(BASELINE.json config 5; see docs/distributed.md).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from specpride_tpu.observability import tracing

CLUSTER_AXIS = "clusters"


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up the multi-host runtime (JAX's coordination service over
    ICI/DCN — the capability slot NCCL/MPI fills in torch frameworks; the
    reference has no equivalent).  No-op if already initialized or
    single-process with no coordinator configured.

    The guard must NOT touch ``jax.process_count()``/``jax.devices()``:
    those initialize the local backend, and ``jax.distributed.initialize``
    is only legal *before* backend init — probing through them would make
    multi-host bring-up self-defeating.  ``jax.distributed.is_initialized``
    reads coordination-service state without spinning up a backend."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if is_init():
            return
    else:
        # older jax: no public probe — the global client object is the
        # coordination-service state (still no backend init involved)
        state = getattr(jax.distributed, "global_state", None)
        if state is not None and getattr(state, "client", None) is not None:
            return
    if coordinator_address is None:
        return  # single-process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def cluster_mesh(devices: list | None = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name "clusters"."""
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs, (CLUSTER_AXIS,))


def cluster_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding that splits the leading (cluster) axis and replicates the
    rest: P("clusters", None, ...)."""
    return NamedSharding(mesh, P(CLUSTER_AXIS, *([None] * (ndim - 1))))


def pad_to_multiple(arr: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad the leading axis up to a multiple of ``multiple`` (sharding
    requires the cluster axis divisible by the mesh size; padded clusters
    have all-False masks and are discarded on unpad)."""
    b = arr.shape[0]
    rem = (-b) % multiple
    if rem == 0:
        return arr
    pad = [(0, rem)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def shard_batch_arrays(mesh: Mesh, *arrays: np.ndarray) -> tuple[jax.Array, ...]:
    """device_put each array with its leading axis split over the mesh.

    Leading axes must already be divisible by the mesh size (use
    ``pad_to_multiple``).  Returns committed sharded jax.Arrays; passing
    them into a jitted kernel makes XLA partition the whole program.
    """
    with tracing.span(
        "h2d:shard", n_arrays=len(arrays),
        bytes=int(sum(int(a.nbytes) for a in arrays)),
        # per-channel dtypes: with --precision the packed channels ship
        # narrowed (bf16/int8/int16), and an operator reading H2D spans
        # in a Chrome trace must see WHAT was on the wire, not just size
        dtypes=",".join(str(a.dtype) for a in arrays),
    ):
        # ONE device_put over the argument list, like the mesh-less
        # _put_batch: per-array puts each pay a full transfer round trip
        # on remote-device hosts (~70 ms measured), and a kernel call
        # ships 2-12 arrays
        out = jax.device_put(
            list(arrays),
            [cluster_sharding(mesh, a.ndim) for a in arrays],
        )
        return tuple(out)
