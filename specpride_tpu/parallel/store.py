"""Pluggable coordinator state store: the small-record CAS layer the
elastic :class:`~specpride_tpu.parallel.coordinator.Coordinator`
protocol (leases + exactly-once commits + split/steal handshake) runs
on top of.

PR 9's coordinator talked to the filesystem directly, so a fleet
without a shared POSIX mount — the cheap preemptible cloud deployment
the ROADMAP's "millions of users" north star implies — could not run
elastically at all.  This module extracts the storage operations the
protocol actually needs into :class:`Store` and ships two backends:

* :class:`FsStore` — the original shared-directory backend, preserved
  byte-for-byte on disk (``leases/range_*.json`` O_EXCL creates, utime
  renewals, tombstone renames, ``done/`` hardlink commit markers), so
  everything PR 9 proved — and every existing journal/merge consumer —
  keeps working unchanged.
* :class:`HttpCasStore` — a conditional-put/ETag object-store client
  speaking the subset every real object store exposes (S3
  ``If-None-Match: *`` / ``If-Match``, GCS ``x-goog-if-generation-
  match``, Azure ETags): create-if-absent, ETag-guarded replace/delete,
  and provider-clock freshness.  ``--elastic http://host:port`` selects
  it.

The protocol was shaped so every mutation maps onto one of FOUR
primitive shapes, each atomic on both backends:

====================  =====================  ==========================
protocol step         FsStore                HttpCasStore
====================  =====================  ==========================
claim / commit /      ``os.link`` from a     ``PUT`` with
propose / ratify      private temp (EEXIST   ``If-None-Match: *``
(``put_new``)         = lost the race)       (412 = lost the race)
lease renewal         ``os.utime`` (atomic   ``PUT`` with ``If-Match:
(``touch``)           mtime bump; can never  <etag>`` re-writing the
                      shadow a stealer's     same body (412 = a stealer
                      fresh lease)           replaced the lease)
expiry steal          nonce-checked rename   ``DELETE`` with
(``delete_if``)       to a tombstone (one    ``If-Match`` (one racer
                      racer's rename wins)   gets 204, the rest 412)
liveness judgment     ``now - st_mtime``     server-computed age header
(``age_s``)           (grace absorbs         (single clock — client
                      client/NFS skew)       skew cannot early-steal)
====================  =====================  ==========================

ETags are content-derived on the filesystem backend (sha256 of the
record bytes — stable across ``utime`` renewals, unique per lease
because every lease carries a fresh nonce) and server-assigned
revisions on the HTTP backend.

:class:`CasServer` is the in-tree test/reference server (stdlib
``ThreadingHTTPServer``, in-memory) so CI and the bench exercise the
object-store protocol without a cloud account: ``specpride cas-server``
runs it standalone.

This module is deliberately jax-free.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid

from specpride_tpu.observability.stats import logger

# request timeout for every object-store round trip: coordinator records
# are tiny, so anything slower than this is an outage the lease TTL
# machinery should see, not a transfer in progress
HTTP_TIMEOUT_S = 10.0


def _etag_of(body: bytes) -> str:
    return hashlib.sha256(body).hexdigest()[:16]


def _encode(payload: dict) -> bytes:
    return (json.dumps(payload) + "\n").encode("utf-8")


def _decode(body: bytes) -> dict | None:
    """Torn/concurrent states decode as None — callers treat that as
    "contested, look again", never as a crash."""
    try:
        data = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    return data if isinstance(data, dict) else None


class Store:
    """The coordinator's storage contract.  Keys are ``/``-separated
    relative paths (``leases/range_00003.json``); payloads are small
    JSON objects.  Every mutator is atomic per key; cross-key
    transactions are deliberately absent — the protocol never needs
    one."""

    def put_new(self, key: str, payload: dict) -> bool:
        """Create-if-absent.  False = the key already exists (something
        else won the race); the caller's claim/commit/proposal lost."""
        raise NotImplementedError

    def get(self, key: str) -> tuple[dict, str] | None:
        """``(payload, etag)`` or None (absent/torn)."""
        raise NotImplementedError

    def put(self, key: str, payload: dict) -> None:
        """Unconditional atomic replace — last writer wins.  Only used
        for single-writer records (a rank's own heartbeat)."""
        raise NotImplementedError

    def touch(self, key: str) -> bool:
        """Refresh the key's freshness (``age_s`` restarts) WITHOUT
        changing its content.  False = the key is gone or was replaced
        out from under us (the caller lost its lease)."""
        raise NotImplementedError

    def delete_if(self, key: str, etag: str) -> bool:
        """Compare-and-delete: remove the key iff its etag still
        matches.  False = mismatch/absent (lost the race)."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Best-effort unconditional delete (release cleanup)."""
        raise NotImplementedError

    def list_keys(self, prefix: str) -> list[str]:
        """Sorted keys under ``prefix`` (one directory level)."""
        raise NotImplementedError

    def age_s(self, key: str) -> float | None:
        """Seconds since the key was last written/touched, judged by
        the STORE's clock (None = absent).  This is the liveness input:
        the grace margin on top of the TTL absorbs whatever skew the
        backend's clock model leaves."""
        raise NotImplementedError

    def get_with_age(
        self, key: str
    ) -> tuple[dict, str, float | None] | None:
        """``(payload, etag, age_s)`` in ONE store round trip where the
        backend can manage it — the claim/steal scans judge liveness on
        every record they read, and paying a second request per key
        against a billed, rate-limited object store would double the
        protocol's traffic."""
        got = self.get(key)
        if got is None:
            return None
        return got[0], got[1], self.age_s(key)

    def describe(self) -> str:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FsStore(Store):
    """Shared-directory backend — PR 9's on-disk layout, unchanged."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # the store's clock, overridable by skew tests
        self._now = time.time

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key)

    def put_new(self, key: str, payload: dict) -> bool:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as fh:
            fh.write(_encode(payload))
        try:
            os.link(tmp, path)  # atomic create-if-absent, full content
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)
        return True

    def get(self, key: str) -> tuple[dict, str] | None:
        try:
            with open(self._path(key), "rb") as fh:
                body = fh.read()
        except OSError:
            return None
        payload = _decode(body)
        if payload is None:
            return None
        return payload, _etag_of(body)

    def put(self, key: str, payload: dict) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as fh:
            fh.write(_encode(payload))
        os.replace(tmp, path)

    def touch(self, key: str) -> bool:
        try:
            os.utime(self._path(key))
        except OSError:
            return False
        return True

    def delete_if(self, key: str, etag: str) -> bool:
        path = self._path(key)
        current = self.get(key)
        if current is None or current[1] != etag:
            return False
        # rename to a tombstone, not unlink: only one racer's rename
        # succeeds, and the debris is post-mortem evidence of the steal
        tomb = f"{path}.dead.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        try:
            os.rename(path, tomb)
        except FileNotFoundError:
            return False
        return True

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def list_keys(self, prefix: str) -> list[str]:
        directory = self._path(prefix.rstrip("/"))
        try:
            names = os.listdir(directory)
        except OSError:
            return []
        clean = prefix.rstrip("/") + "/"
        return sorted(
            clean + name
            for name in names
            if not name.endswith(".lock")
            and ".tmp." not in name and ".dead." not in name
        )

    def age_s(self, key: str) -> float | None:
        try:
            mtime = os.stat(self._path(key)).st_mtime
        except OSError:
            return None
        return max(self._now() - mtime, 0.0)

    def describe(self) -> str:
        return f"filesystem:{self.root}"


class HttpCasStore(Store):
    """Conditional-put/ETag object-store client (``--elastic URL``).

    Every mutation is one HTTP round trip; conflicts come back as 412
    (Precondition Failed) and map onto the same False/None returns the
    filesystem backend produces, so the coordinator protocol above is
    backend-blind.  Freshness (``age_s``) is the server-computed
    ``X-SpecPride-Age`` header — a single clock, so a skewed client can
    never judge a live lease expired early."""

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")

    def _url(self, key: str) -> str:
        return f"{self.base_url}/{key}"

    def _request(self, method: str, key: str, body: bytes | None = None,
                 headers: dict | None = None):
        req = urllib.request.Request(
            self._url(key), data=body, method=method,
            headers=headers or {},
        )
        return urllib.request.urlopen(req, timeout=HTTP_TIMEOUT_S)

    def put_new(self, key: str, payload: dict) -> bool:
        try:
            with self._request(
                "PUT", key, _encode(payload), {"If-None-Match": "*"}
            ):
                return True
        except urllib.error.HTTPError as e:
            if e.code == 412:
                return False
            raise

    def get(self, key: str) -> tuple[dict, str] | None:
        try:
            with self._request("GET", key) as resp:
                body = resp.read()
                etag = resp.headers.get("ETag", "")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        payload = _decode(body)
        if payload is None:
            return None
        return payload, etag.strip('"')

    def put(self, key: str, payload: dict) -> None:
        with self._request("PUT", key, _encode(payload)):
            pass

    def touch(self, key: str) -> bool:
        current = self.get(key)
        if current is None:
            return False
        payload, etag = current
        try:
            with self._request(
                "PUT", key, _encode(payload), {"If-Match": f'"{etag}"'}
            ):
                return True
        except urllib.error.HTTPError as e:
            if e.code in (404, 412):
                return False
            raise

    def delete_if(self, key: str, etag: str) -> bool:
        try:
            with self._request(
                "DELETE", key, headers={"If-Match": f'"{etag}"'}
            ):
                return True
        except urllib.error.HTTPError as e:
            if e.code in (404, 412):
                return False
            raise

    def delete(self, key: str) -> None:
        try:
            with self._request("DELETE", key):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def list_keys(self, prefix: str) -> list[str]:
        url = f"{self.base_url}/?prefix={urllib.parse.quote(prefix)}"
        req = urllib.request.Request(url)
        with urllib.request.urlopen(req, timeout=HTTP_TIMEOUT_S) as resp:
            data = json.loads(resp.read().decode("utf-8"))
        keys = data.get("keys", []) if isinstance(data, dict) else []
        return sorted(k for k in keys if isinstance(k, str))

    def age_s(self, key: str) -> float | None:
        try:
            with self._request("GET", key) as resp:
                age = resp.headers.get("X-SpecPride-Age")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        try:
            return max(float(age), 0.0)
        except (TypeError, ValueError):
            return None

    def get_with_age(
        self, key: str
    ) -> tuple[dict, str, float | None] | None:
        """Body, ETag and the server-computed age off ONE GET."""
        try:
            with self._request("GET", key) as resp:
                body = resp.read()
                etag = resp.headers.get("ETag", "")
                age = resp.headers.get("X-SpecPride-Age")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        payload = _decode(body)
        if payload is None:
            return None
        try:
            age_s = max(float(age), 0.0)
        except (TypeError, ValueError):
            age_s = None
        return payload, etag.strip('"'), age_s

    def describe(self) -> str:
        return f"object-store:{self.base_url}"


def store_from_spec(spec: str) -> Store:
    """``--elastic`` value -> backend: an ``http(s)://`` URL selects the
    object-store client, anything else is a shared directory."""
    if spec.startswith(("http://", "https://")):
        return HttpCasStore(spec)
    return FsStore(spec)


def is_remote_spec(spec: str) -> bool:
    return spec.startswith(("http://", "https://"))


# -- the in-tree CAS test server ----------------------------------------


class CasServer:
    """In-memory conditional-put object store over HTTP — the reference
    implementation of the contract :class:`HttpCasStore` consumes, so
    CI's preemption-storm pass and the bench's backend-overhead cell
    run the REAL wire protocol with no cloud account.

    Semantics (the subset S3/GCS/Azure all offer):

    * ``PUT`` — unconditional replace; ``If-None-Match: *`` = create
      only (412 if present); ``If-Match: <etag>`` = replace only if
      unchanged (412 otherwise).  Replies carry the new ``ETag``.
    * ``GET`` — body + ``ETag`` + ``X-SpecPride-Age`` (seconds since
      last write, SERVER clock — the skew-proof liveness input).
      ``GET /?prefix=P`` lists keys.
    * ``DELETE`` — optional ``If-Match`` precondition.

    ETags are server-assigned revisions (``"<rev>-<sha12>"``): two
    writes of identical bytes still produce distinct etags, so an
    etag-guarded steal can never confuse a re-claimed lease with the
    one it read."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self._lock = threading.Lock()
        # key -> (body, etag, last_write_monotonic)
        self._data: dict[str, tuple[bytes, str, float]] = {}
        self._rev = 0
        store = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # stdlib default spams stderr
                pass

            def _key(self) -> str:
                return self.path.lstrip("/").split("?", 1)[0]

            def _reply(self, code: int, body: bytes = b"",
                       headers: dict | None = None) -> None:
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                if "?" in self.path and "prefix=" in self.path:
                    prefix = urllib.parse.unquote(
                        self.path.split("prefix=", 1)[1].split("&", 1)[0]
                    )
                    with store._lock:
                        keys = sorted(
                            k for k in store._data if k.startswith(prefix)
                        )
                    self._reply(
                        200, json.dumps({"keys": keys}).encode(),
                        {"Content-Type": "application/json"},
                    )
                    return
                with store._lock:
                    rec = store._data.get(self._key())
                    now = time.monotonic()
                if rec is None:
                    self._reply(404)
                    return
                body, etag, written = rec
                self._reply(200, body, {
                    "ETag": f'"{etag}"',
                    "X-SpecPride-Age": f"{max(now - written, 0.0):.3f}",
                    "Content-Type": "application/json",
                })

            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                key = self._key()
                if_none = self.headers.get("If-None-Match")
                if_match = self.headers.get("If-Match")
                with store._lock:
                    existing = store._data.get(key)
                    if if_none == "*" and existing is not None:
                        self._reply(412)
                        return
                    if if_match is not None and (
                        existing is None
                        or existing[1] != if_match.strip('"')
                    ):
                        self._reply(412)
                        return
                    store._rev += 1
                    etag = (
                        f"{store._rev}-"
                        f"{hashlib.sha256(body).hexdigest()[:12]}"
                    )
                    store._data[key] = (body, etag, time.monotonic())
                self._reply(
                    201 if existing is None else 200, b"",
                    {"ETag": f'"{etag}"'},
                )

            def do_DELETE(self):
                key = self._key()
                if_match = self.headers.get("If-Match")
                with store._lock:
                    existing = store._data.get(key)
                    if existing is None:
                        self._reply(404)
                        return
                    if if_match is not None and (
                        existing[1] != if_match.strip('"')
                    ):
                        self._reply(412)
                        return
                    del store._data[key]
                self._reply(204)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "CasServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="specpride-cas-server",
            daemon=True,
        )
        self._thread.start()
        logger.info("CAS server listening on %s", self.url)
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
