"""Warm-spare fleet supervisor: ``specpride fleet``.

A preemptible fleet needs someone to notice that a rank evaporated and
to decide when extra capacity is worth paying for.  The elastic
coordinator already makes rank DEATH safe (lease expiry + reassignment)
and rank SLOWNESS recoverable (live work-stealing) — this module closes
the loop by managing the rank processes themselves:

* keep ``--ranks N`` workers running while uncommitted ranges remain;
* **scale up** — spawn up to ``--spares M`` extra workers (bounded by
  ``--max-ranks``) when the fleet looks unhealthy or behind: a
  heartbeat older than the lease TTL + grace (a rank presumed dead or
  badly stalled — its work is about to be reassigned, so capacity to
  absorb it should already be warm), or a completion horizon
  (``remaining ranges / committed rate``) beyond ``--scale-horizon``
  seconds;
* **scale down** — SIGTERM workers that the store shows idle (holding
  no leases) once fewer ranges remain than workers; an idle warm spare
  costs a slot on the machine, nothing in the run (it would linger
  polling until the fleet finishes);
* **replace** — a worker that exits abnormally (preemption, SIGKILL,
  OOM) is respawned while claimable work remains.

Every decision is journaled: ``rank_spawn`` (``reason`` ∈ ``boot`` /
``replace_dead`` / ``scale_up``) and ``rank_retire`` (``reason`` =
``excess_capacity``) — so a post-mortem reads autoscaling the same way
it reads leases.  The supervisor itself holds NO lease and writes no
output; killing it mid-run loses nothing (workers finish or age out
like any other rank).

Worker processes are the ordinary CLI: the supervised argv is a
complete ``specpride consensus/select … --elastic SPEC`` command line
WITHOUT ``--process-id`` (each worker auto-assigns a fresh rank id).
This module is jax-free: supervision is pure process + store watching.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

from specpride_tpu.observability.stats import logger
from specpride_tpu.parallel.store import Store, store_from_spec

# default seconds of projected remaining work that justifies warming a
# spare: small enough to react within one CI-scale run, large enough
# that a healthy fleet finishing soon is left alone
DEFAULT_SCALE_HORIZON_S = 60.0


def extract_flag(argv: list[str], flag: str) -> str | None:
    """The value of ``--flag VALUE`` or ``--flag=VALUE`` in a job argv
    (last occurrence wins, like argparse)."""
    value = None
    for i, tok in enumerate(argv):
        if tok == flag and i + 1 < len(argv):
            value = argv[i + 1]
        elif tok.startswith(flag + "="):
            value = tok.split("=", 1)[1]
    return value


class FleetSupervisor:
    """Drive one elastic run to completion with ``ranks`` workers and up
    to ``spares`` warm spares.  :meth:`run` blocks until every range is
    committed (returns 0) or no worker can make progress (returns 1).

    ``env`` is the spawned workers' environment; ``cmd_fleet`` stamps
    the supervisor's trace context into it (``SPECPRIDE_TRACE``,
    ``trace_id:span_id``) so every rank — boot workers, replacements,
    scaled-up spares — journals under ONE trace and ``specpride trace``
    merges the whole fleet onto a single causal timeline."""

    def __init__(
        self,
        job_argv: list[str],
        ranks: int,
        spares: int = 0,
        max_ranks: int | None = None,
        journal=None,
        poll_interval: float = 0.5,
        scale_horizon: float = DEFAULT_SCALE_HORIZON_S,
        env: dict | None = None,
        autotune: str = "off",
        flightrec: str = "off",
        incident_dir: str | None = None,
    ):
        spec = extract_flag(job_argv, "--elastic")
        if not spec:
            raise ValueError(
                "fleet needs an --elastic DIR|URL in the supervised argv"
            )
        if extract_flag(job_argv, "--process-id") is not None:
            raise ValueError(
                "drop --process-id from the supervised argv: every "
                "spawned worker must auto-assign a fresh rank"
            )
        self.job_argv = list(job_argv)
        self.spec = spec
        self.ranks = max(int(ranks), 0)
        self.spares = max(int(spares), 0)
        self.max_ranks = (
            int(max_ranks) if max_ranks else self.ranks + self.spares
        )
        self.journal = journal
        self.poll_interval = max(float(poll_interval), 0.05)
        self.scale_horizon = max(float(scale_horizon), 1.0)
        self.env = dict(env if env is not None else os.environ)
        ttl = extract_flag(job_argv, "--elastic-ttl")
        try:
            self.ttl = float(ttl) if ttl else 10.0
        except ValueError:
            self.ttl = 10.0
        self.grace = self.ttl * 0.5
        self.store: Store = store_from_spec(spec)
        # per-worker stderr lands in files, never a pipe: an undrained
        # pipe blocks a chatty worker's writes once the OS buffer fills
        # (the supervisor only reads stderr AFTER exit)
        self.scratch = tempfile.mkdtemp(prefix="specpride-fleet-")
        self.procs: list[subprocess.Popen] = []
        self.spawned = 0
        self.retired = 0
        self.replaced = 0
        self.failures: list[str] = []
        self._done_cache: set[str] = set()
        if autotune not in ("off", "observe", "on"):
            raise ValueError(
                f"fleet autotune {autotune!r} must be off, observe or on"
            )
        self.autotune = autotune
        self.controller = None
        if autotune != "off":
            if journal is None or not getattr(journal, "enabled", False):
                raise ValueError(
                    "fleet --autotune observe|on requires --journal: "
                    "every decision must be journaled as evidence"
                )
            from specpride_tpu.autotune.controller import Controller
            from specpride_tpu.autotune.policy import FleetSparesPolicy
            ctl = Controller(journal, mode=autotune)
            ctl.register(
                FleetSparesPolicy(
                    lo=0, hi=max(self.max_ranks - self.ranks, 0),
                ),
                get=lambda: self.spares,
                set=lambda n: setattr(self, "spares", max(int(n), 0)),
            )
            self.controller = ctl
        # flight recorder: off constructs nothing (byte-identical to a
        # recorder-free supervisor); observe/on fold the fleet journal
        # into health detectors next to the controller's tap
        if flightrec not in ("off", "observe", "on"):
            raise ValueError(
                f"fleet flightrec {flightrec!r} must be off, observe "
                "or on"
            )
        self.flightrec = flightrec
        self.recorder = None
        if flightrec != "off":
            if journal is None or not getattr(journal, "enabled", False):
                raise ValueError(
                    "fleet --flightrec observe|on requires --journal: "
                    "the detectors fold the journal stream"
                )
            from specpride_tpu.observability.flightrec import (
                FlightRecorder,
            )
            ctl = self.controller
            self.recorder = FlightRecorder(
                journal,
                mode=flightrec,
                incident_dir=incident_dir,
                autotune_fn=(
                    (lambda: {"status": ctl.status(),
                              "knobs": ctl.knob_values()})
                    if ctl is not None else None
                ),
                extra_fn=lambda: {
                    "procs_alive": sum(
                        1 for p in self.procs if p.poll() is None
                    ),
                    "spawned": self.spawned,
                    "retired": self.retired,
                    "replaced": self.replaced,
                    "failures": list(self.failures),
                },
                config={
                    "host": "fleet",
                    "elastic": self.spec,
                    "ranks": self.ranks,
                    "spares": self.spares,
                    "max_ranks": self.max_ranks,
                    "ttl_s": self.ttl,
                    "autotune": autotune,
                    "flightrec": flightrec,
                },
            ).start()

    # -- store views -----------------------------------------------------

    def _plan(self) -> dict | None:
        got = self.store.get("plan.json")
        return got[0] if got is not None else None

    def _range_ids(self) -> set[int]:
        plan = self._plan()
        if plan is None:
            return set()
        ids = set(range(int(plan.get("n_ranges", 0) or 0)))
        # split-off tails count from their CUT records (the atomic
        # publication) — an overlay id allocated by a donor that died
        # mid-handshake has no cut, is claimable by nobody, and must
        # not inflate the remaining-work count forever
        for key in self.store.list_keys("split/"):
            if ".cut." not in key:
                continue
            got = self.store.get(key)
            if got is not None and isinstance(got[0].get("new_range"), int):
                ids.add(got[0]["new_range"])
        return ids

    def _done_ids(self) -> set[str]:
        for key in self.store.list_keys("done/"):
            self._done_cache.add(key)
        return self._done_cache

    def _heartbeats(self) -> list[tuple[dict, float]]:
        out = []
        for key in self.store.list_keys("hb/"):
            got = self.store.get_with_age(key)
            if got is not None and got[2] is not None:
                out.append((got[0], got[2]))
        return out

    # -- process management ----------------------------------------------

    def _spawn(self, reason: str) -> None:
        argv = [sys.executable, "-m", "specpride_tpu"] + self.job_argv
        err_path = os.path.join(
            self.scratch, f"worker-{self.spawned:04d}.stderr"
        )
        with open(err_path, "wb") as err_fh:
            proc = subprocess.Popen(
                argv, env=self.env,
                stdout=subprocess.DEVNULL, stderr=err_fh,
            )
        proc.stderr_path = err_path  # type: ignore[attr-defined]
        self.procs.append(proc)
        self.spawned += 1
        if self.journal is not None:
            self.journal.emit("rank_spawn", pid=proc.pid, reason=reason)
        logger.info("fleet: spawned worker pid %d (%s)", proc.pid, reason)

    def _retire(self, proc: subprocess.Popen, reason: str) -> None:
        try:
            proc.send_signal(signal.SIGTERM)
        except OSError:
            return
        self.retired += 1
        if self.journal is not None:
            self.journal.emit("rank_retire", pid=proc.pid, reason=reason)
        logger.info("fleet: retiring worker pid %d (%s)", proc.pid, reason)

    def _reap(self, work_remains: bool) -> None:
        """Collect exited workers; replace abnormal exits while work
        remains (a clean exit 0 means the worker saw every range
        committed — no replacement needed)."""
        alive: list[subprocess.Popen] = []
        for proc in self.procs:
            rc = proc.poll()
            if rc is None:
                alive.append(proc)
                continue
            err = b""
            try:
                with open(proc.stderr_path, "rb") as fh:
                    err = fh.read()
            except OSError:
                pass
            if rc != 0 and rc != -signal.SIGTERM:
                tail = err.decode(errors="replace")[-2000:]
                logger.warning(
                    "fleet: worker pid %d exited %s%s", proc.pid, rc,
                    f"\n{tail}" if tail.strip() else "",
                )
                if work_remains:
                    self.replaced += 1
                    self._spawn("replace_dead")
                else:
                    self.failures.append(
                        f"pid {proc.pid} exited {rc} with no work left"
                    )
        self.procs = alive

    # -- the policy loop -------------------------------------------------

    def _desired(self, remaining: int, rate: float) -> int:
        """How many workers to keep alive right now."""
        if remaining <= 0:
            return 0
        target = self.ranks
        # a rank whose heartbeat went silent past TTL + grace WITHOUT
        # the clean-shutdown marker is presumed dead or badly stalled —
        # capacity to absorb its reassigned work should already be warm
        stale = any(
            age > hb.get("ttl", self.ttl) + self.grace
            for hb, age in self._heartbeats()
            if not hb.get("stopped")
        )
        # the horizon trigger needs an OBSERVED commit rate: before the
        # first commits land, rate 0 says "unknown", not "infinitely
        # behind" — stale heartbeats are the early-trouble signal
        behind = rate > 0 and (remaining / rate) > self.scale_horizon
        if stale or behind:
            target = self.ranks + self.spares
        # never more workers than claimable units of work — a worker
        # beyond that could only idle (an existing spare already covers
        # the warm-takeover case).  A pure-spare supervisor (--ranks 0
        # watching externally-launched ranks) floors at zero: it adds
        # capacity only when the policy above asks for it.
        floor = 1 if self.ranks > 0 else 0
        return max(min(target, self.max_ranks, remaining), floor)

    def run(self, timeout: float | None = None) -> int:
        deadline = (
            time.perf_counter() + timeout if timeout is not None else None
        )
        t0 = time.perf_counter()
        for _ in range(self.ranks):
            self._spawn("boot")
        rate_window: list[tuple[float, int]] = []  # (mono, n_done)
        try:
            while True:
                if deadline is not None and time.perf_counter() > deadline:
                    self.failures.append("fleet timeout")
                    return 1
                ids = self._range_ids()
                done = {
                    key for key in self._done_ids()
                }
                remaining = max(len(ids) - len(done), 0) if ids else None
                now = time.perf_counter()
                rate_window.append((now, len(done)))
                rate_window[:] = [
                    (t, n) for t, n in rate_window if now - t <= 10.0
                ]
                rate = 0.0
                if len(rate_window) >= 2:
                    dt = rate_window[-1][0] - rate_window[0][0]
                    dn = rate_window[-1][1] - rate_window[0][1]
                    rate = dn / dt if dt > 0 else 0.0
                work_remains = remaining is None or remaining > 0
                self._reap(work_remains)
                if remaining == 0:
                    # ranges all committed: workers exit on their own
                    # (their claim loop sees all_committed) — wait for
                    # them, then report
                    for proc in self.procs:
                        try:
                            proc.wait(timeout=max(self.ttl * 4, 30.0))
                        except subprocess.TimeoutExpired:
                            proc.kill()
                            self.failures.append(
                                f"pid {proc.pid} hung after completion"
                            )
                    self._reap(work_remains=False)
                    self.procs = []
                    return 1 if self.failures else 0
                if remaining is not None:
                    desired = self._desired(remaining, rate)
                    while len(self.procs) < desired:
                        self._spawn(
                            "scale_up" if self.spawned >= self.ranks
                            else "boot"
                        )
                    if len(self.procs) > desired and remaining < len(
                        self.procs
                    ):
                        self._scale_down(len(self.procs) - desired)
                elif not self.procs and time.perf_counter() - t0 > 60.0:
                    # no plan after a generous boot window and nobody
                    # alive to write one — a --ranks 0 supervisor is
                    # waiting for externally-launched ranks that never
                    # registered
                    self.failures.append(
                        "no worker alive and no plan registered"
                    )
                    return 1
                if self.controller is not None:
                    # synchronous tick from the poll loop (no thread):
                    # the store-derived pressure view rides the decision
                    # as snapshot extras — recorded evidence, since it
                    # is not derivable from this journal alone
                    proposals = sum(
                        1 for key in self.store.list_keys("split/")
                        if ".cut." not in key
                    )
                    stale = sum(
                        1 for hb, age in self._heartbeats()
                        if not hb.get("stopped")
                        and age > hb.get("ttl", self.ttl) + self.grace
                    )
                    self.controller.tick({
                        "steal_proposals": proposals,
                        "stale_ranks": stale,
                    })
                time.sleep(self.poll_interval)
        finally:
            if self.controller is not None:
                self.controller.close()
            if self.recorder is not None:
                # drains queued firings into the journal BEFORE the
                # caller closes it — a dying fleet keeps its evidence
                self.recorder.stop()
            for proc in self.procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc in self.procs:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()

    def _scale_down(self, n: int) -> None:
        """Retire up to ``n`` workers the store shows IDLE (no held
        leases): pid -> rank via the heartbeat records each rank
        publishes about itself."""
        idle_pids = {
            hb.get("pid")
            for hb, age in self._heartbeats()
            if not hb.get("holding") and age <= self.ttl
        }
        for proc in list(self.procs):
            if n <= 0:
                break
            if proc.poll() is None and proc.pid in idle_pids:
                self._retire(proc, "excess_capacity")
                n -= 1

    def summary(self) -> dict:
        return {
            "spawned": self.spawned,
            "retired": self.retired,
            "replaced": self.replaced,
            "failures": list(self.failures),
            **(
                {"autotune": {
                    **self.controller.status(), "spares": self.spares,
                }}
                if self.controller is not None else {}
            ),
            **(
                {"flightrec": self.recorder.status()}
                if self.recorder is not None else {}
            ),
        }
