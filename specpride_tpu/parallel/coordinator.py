"""Filesystem-backed work coordinator for elastic multi-host runs.

The static ``_shard_for_process`` partition assumes a fixed healthy rank
set: each rank owns one contiguous block of clusters for the whole run,
and a rank that dies loses its block.  This module replaces the one-shot
partition with **dynamic distribution of chunk ranges** over a shared
directory — no network service beyond the filesystem every rank already
mounts:

* ``plan.json`` — the deterministic work plan: ``n_clusters`` split into
  fixed cluster-index **ranges** of ``range_size``.  Every rank derives
  the identical plan from its own input parse; the first rank persists
  it atomically and later ranks verify theirs matches, so a fleet run
  against divergent inputs fails loudly instead of merging garbage.
* ``leases/range_<k>.json`` — at most one rank works a range at a time.
  A claim is an ``O_EXCL`` create (atomic on POSIX and NFSv3+); the
  holder renews by bumping the file's MTIME (``os.utime`` — atomic, so
  a renewal can never overwrite a lease a stealer just re-created).  A
  lease whose mtime is older than the holder's TTL (plus a grace margin
  against clock skew) may be **stolen**: the observer renames it to a
  tombstone — only one racer's rename succeeds — re-claims the range,
  and only then journals ``lease_expire`` + ``chunk_reassign`` (losing
  the re-claim race emits nothing: the winner's events cover it).
* ``done/range_<k>.json`` — the commit marker: ``os.link`` from a
  private temp file, so two ranks racing the same range commit exactly
  once (link fails with ``EEXIST`` for the loser).  The marker carries
  the range part file's ``output_bytes`` + ``sha256`` from the schema-2
  checkpoint manifest, which is what ``merge-parts --elastic`` verifies
  before concatenating.
* ``hb/rank_<r>.json`` — per-rank heartbeat files (atomic replace), the
  live view the metrics exporter samples; each beat is also journaled
  as a ``heartbeat`` event so post-mortems can reconstruct liveness
  from the ``.part<rank>`` journals alone.
* ``ranks/`` — ``O_EXCL`` rank auto-assignment when ``--process-id`` is
  not given: ranks need stable identities for journals/heartbeats, not
  a fixed count.

Fencing: the holder's lease carries a per-claim ``nonce``.  Before each
chunk commit the executor calls :meth:`Coordinator.check_lease`; a
missing lease or a foreign nonce raises
:class:`~specpride_tpu.robustness.errors.LeaseExpiredError` (permanent —
never retried), so a rank that stalled past its TTL abandons the range
instead of racing the rank that took it over.  The window between the
check and the append is the residual risk; the commit-marker link and
the merge-time sha256 verification catch anything that slips through,
loudly.

This module is deliberately jax-free: the coordinator runs identically
on a login node, a CI box, or a TPU host.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import threading
import time
import uuid

from specpride_tpu.observability.stats import logger
from specpride_tpu.robustness.errors import LeaseExpiredError

PLAN_SCHEMA = 1
DONE_SCHEMA = 1

# default lease time-to-live and the grace margin an observer adds on
# top before declaring a lease dead (absorbs clock skew between hosts
# sharing the directory over NFS)
DEFAULT_TTL_S = 10.0
DEFAULT_GRACE_FRAC = 0.5


@dataclasses.dataclass(frozen=True)
class ChunkRange:
    """One unit of claimable work: a contiguous block of cluster
    indices.  Ranges are fixed by the plan — deterministic chunk-range
    addressing — so every rank, and every post-mortem, resolves range
    ``k`` to the same clusters and the same ``.part<k>`` output."""

    range_id: int
    start: int
    stop: int

    @property
    def n_clusters(self) -> int:
        return self.stop - self.start


@dataclasses.dataclass
class Claim:
    """A held lease on one range."""

    range: ChunkRange
    nonce: str
    takeover: bool = False
    from_rank: int | None = None
    lost: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )


def plan_ranges(n_clusters: int, range_size: int) -> list[ChunkRange]:
    """The deterministic plan: ``n_clusters`` in blocks of
    ``range_size``.  An empty input still plans ONE empty range so the
    claimer writes an empty part and ``merge-parts`` finds something."""
    size = max(int(range_size), 1)
    if n_clusters <= 0:
        return [ChunkRange(0, 0, 0)]
    return [
        ChunkRange(k, start, min(start + size, n_clusters))
        for k, start in enumerate(range(0, n_clusters, size))
    ]


def _write_atomic(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
        fh.write("\n")
    os.replace(tmp, path)


def _read_json(path: str) -> dict | None:
    """Best-effort read of a small coordinator file.  Torn/concurrent
    states read as None — callers treat that as "contested, look again"
    rather than crashing a surviving rank on a dying rank's debris."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


class Coordinator:
    """One rank's handle on the shared elastic work queue.

    Construction registers the plan (or verifies it against the one a
    peer already wrote) and starts the heartbeat thread; callers MUST
    pair with :meth:`stop` (the CLI does so in a ``finally``)."""

    def __init__(
        self,
        root: str,
        rank: int,
        n_clusters: int,
        range_size: int,
        ttl: float = DEFAULT_TTL_S,
        heartbeat_interval: float = 0.0,
        journal=None,
    ):
        self.root = os.path.abspath(root)
        self.rank = int(rank)
        self.ttl = max(float(ttl), 0.1)
        self.grace = self.ttl * DEFAULT_GRACE_FRAC
        self.heartbeat_interval = (
            float(heartbeat_interval)
            if heartbeat_interval and heartbeat_interval > 0
            else max(self.ttl / 4.0, 0.05)
        )
        self.journal = journal
        self.ranges = plan_ranges(n_clusters, range_size)
        self.n_clusters = int(n_clusters)
        self.range_size = max(int(range_size), 1)
        # observed-recovery counters the liveness exporter mirrors
        self.lease_expires_observed = 0
        self.reassignments = 0
        self.ranges_run = 0
        self._lock = threading.Lock()
        self._held: dict[int, Claim] = {}
        self._stop = threading.Event()
        for sub in ("leases", "done", "hb", "ranks", "ck"):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)
        self._register_plan()
        # one immediate beat before the loop: every rank's journal holds
        # at least one heartbeat (the stats rank view keys off it) and
        # the exporter's age gauge starts near zero, even on runs that
        # finish inside the first interval
        self._beat()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"specpride-heartbeat-r{self.rank}", daemon=True,
        )
        self._hb_thread.start()

    # -- plan -----------------------------------------------------------

    def _plan_payload(self) -> dict:
        return {
            "schema": PLAN_SCHEMA,
            "n_clusters": self.n_clusters,
            "range_size": self.range_size,
            "n_ranges": len(self.ranges),
        }

    def _register_plan(self) -> None:
        path = os.path.join(self.root, "plan.json")
        payload = self._plan_payload()
        tmp = f"{path}.tmp.{self.rank}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
            fh.write("\n")
        try:
            os.link(tmp, path)  # atomic create-if-absent
        except FileExistsError:
            pass
        finally:
            os.unlink(tmp)
        existing = _read_json(path)
        if existing is None:
            raise SystemExit(
                f"elastic plan {path} is unreadable — another rank wrote "
                "a torn plan or the directory is not a shared filesystem"
            )
        for key in ("n_clusters", "range_size"):
            if existing.get(key) != payload[key]:
                raise SystemExit(
                    f"elastic plan mismatch in {path}: this rank derived "
                    f"{key}={payload[key]} but the registered plan says "
                    f"{existing.get(key)} — are all ranks running the "
                    "same input and --elastic-range?"
                )

    @classmethod
    def read_plan(cls, root: str) -> dict | None:
        """The registered plan, for ``merge-parts --elastic`` and the
        stats/exporter consumers (None when absent/unreadable)."""
        return _read_json(os.path.join(root, "plan.json"))

    # -- rank identity --------------------------------------------------

    @staticmethod
    def assign_rank(root: str, limit: int = 4096) -> int:
        """Auto-assign the lowest free rank id via ``O_EXCL`` marker
        files — used when ``--process-id`` is not given.  Ranks are
        identities, not a partition: any number may join or rejoin."""
        ranks_dir = os.path.join(root, "ranks")
        os.makedirs(ranks_dir, exist_ok=True)
        for r in range(limit):
            path = os.path.join(ranks_dir, f"rank_{r:05d}")
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                continue
            with os.fdopen(fd, "w") as fh:
                fh.write(f"{os.getpid()}\n")
            return r
        raise SystemExit(f"no free rank id under {ranks_dir}")

    # -- paths ----------------------------------------------------------

    def lease_path(self, k: int) -> str:
        return os.path.join(self.root, "leases", f"range_{k:05d}.json")

    def done_path(self, k: int) -> str:
        return os.path.join(self.root, "done", f"range_{k:05d}.json")

    def checkpoint_path(self, k: int) -> str:
        """The per-range resume manifest — coordinator-owned so elastic
        runs are ALWAYS checkpointed (reassignment needs the manifest to
        know which chunks the dead rank committed)."""
        return os.path.join(self.root, "ck", f"range_{k:05d}.json")

    def heartbeat_path(self, rank: int | None = None) -> str:
        r = self.rank if rank is None else rank
        return os.path.join(self.root, "hb", f"rank_{r:05d}.json")

    # -- leases ---------------------------------------------------------

    def _is_done(self, k: int) -> bool:
        return os.path.exists(self.done_path(k))

    def _create_lease(self, k: int, nonce: str) -> bool:
        # liveness rides the file MTIME, not a stored expiry: renewal is
        # then an atomic os.utime that can never overwrite (shadow) a
        # lease a stealer just re-created the way a read-then-replace
        # rewrite could.  `ttl` is stored so observers judge expiry by
        # the HOLDER's declared cadence, not their own flag.
        payload = {
            "rank": self.rank,
            "pid": os.getpid(),
            "nonce": nonce,
            "claimed": time.time(),
            "ttl": self.ttl,
        }
        try:
            fd = os.open(
                self.lease_path(k), os.O_CREAT | os.O_EXCL | os.O_WRONLY
            )
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as fh:
            json.dump(payload, fh)
            fh.write("\n")
        return True

    def _lease_expired(self, k: int, lease: dict) -> tuple[bool, float]:
        """(expired?, seconds past deadline) judged from the lease
        file's mtime — the renewal heartbeat — plus the holder's TTL and
        the clock-skew grace."""
        try:
            mtime = os.stat(self.lease_path(k)).st_mtime
        except OSError:
            return False, 0.0  # mid-steal — look again next scan
        ttl = lease.get("ttl")
        if not isinstance(ttl, (int, float)) or ttl <= 0:
            ttl = self.ttl
        over = time.time() - (mtime + ttl + self.grace)
        return over > 0, max(over, 0.0)

    def _remaining_clusters(self, rng: ChunkRange) -> int:
        """Clusters of ``rng`` NOT yet committed in its checkpoint
        manifest — the chunk_reassign payload's honest remainder."""
        manifest = _read_json(self.checkpoint_path(rng.range_id))
        if not manifest:
            return rng.n_clusters
        done = manifest.get("done")
        n_done = len(done) if isinstance(done, list) else 0
        return max(rng.n_clusters - n_done, 0)

    def _try_claim(self, rng: ChunkRange) -> Claim | None:
        k = rng.range_id
        nonce = uuid.uuid4().hex
        if self._create_lease(k, nonce):
            claim = Claim(rng, nonce)
            manifest = _read_json(self.checkpoint_path(k))
            if manifest:
                # a prior holder died after its lease was cleaned up (or
                # released without committing): partial state exists, so
                # this fresh-looking claim is still a takeover
                claim.takeover = True
            self._note_claim(claim)
            return claim
        lease = _read_json(self.lease_path(k))
        if lease is None:
            return None  # torn or mid-steal — look again next scan
        # (a dead previous incarnation of THIS rank id is handled like
        # any other dead rank: its lease simply ages out below)
        expired, over_s = self._lease_expired(k, lease)
        if not expired:
            return None  # live holder
        # expired: steal atomically — only one racer's rename succeeds
        tomb = (
            f"{self.lease_path(k)}.dead.{self.rank}.{uuid.uuid4().hex[:8]}"
        )
        try:
            os.rename(self.lease_path(k), tomb)
        except FileNotFoundError:
            return None  # lost the steal race
        dead_rank = lease.get("rank", -1)
        if not self._create_lease(k, nonce):
            # another claimer slipped into the gap between our tombstone
            # rename and our create: ITS lease_claim covers the range,
            # so emit NOTHING here — a lease_expire with no paired
            # chunk_reassign would fail the audit over zero lost work
            return None
        self.lease_expires_observed += 1
        self.reassignments += 1
        if self.journal is not None:
            self.journal.emit(
                "lease_expire", rank=dead_rank, range=k,
                observed_by=self.rank, expired_for_s=round(over_s, 3),
            )
        logger.warning(
            "rank %d: lease on range %d held by rank %s expired; "
            "reassigning", self.rank, k, dead_rank,
        )
        claim = Claim(rng, nonce, takeover=True, from_rank=dead_rank)
        if self.journal is not None:
            self.journal.emit(
                "chunk_reassign", range=k, from_rank=dead_rank,
                to_rank=self.rank,
                n_clusters_remaining=self._remaining_clusters(rng),
            )
        self._note_claim(claim)
        return claim

    def _note_claim(self, claim: Claim) -> None:
        k = claim.range.range_id
        with self._lock:
            self._held[k] = claim
        self.ranges_run += 1
        if self.journal is not None:
            self.journal.emit(
                "lease_claim", rank=self.rank, range=k,
                takeover=claim.takeover,
                **(
                    {"from_rank": claim.from_rank}
                    if claim.from_rank is not None else {}
                ),
            )

    def _holds(self, k: int) -> bool:
        with self._lock:
            return k in self._held

    def claim_next(self) -> Claim | None:
        """Claim the next available range, scanning from this rank's own
        offset (ranks start at different ranges, so a healthy fleet
        claims disjoint work without ever contending).  None = nothing
        claimable right now (all done, or every open range is leased by
        a live rank — poll again)."""
        n = len(self.ranges)
        for i in range(n):
            rng = self.ranges[(self.rank + i) % n]
            if self._is_done(rng.range_id):
                continue
            claim = self._try_claim(rng)
            if claim is not None:
                return claim
        return None

    def all_committed(self) -> bool:
        return all(self._is_done(r.range_id) for r in self.ranges)

    def done_count(self) -> int:
        return sum(self._is_done(r.range_id) for r in self.ranges)

    def check_lease(self, k: int) -> None:
        """The per-commit fence: raise
        :class:`LeaseExpiredError` when this rank no longer holds range
        ``k`` — the lease file is gone (stolen) or carries a foreign
        nonce (stolen and re-claimed)."""
        with self._lock:
            claim = self._held.get(k)
        if claim is None or claim.lost.is_set():
            raise LeaseExpiredError(
                f"rank {self.rank} lost its lease on range {k}"
            )
        lease = _read_json(self.lease_path(k))
        if lease is None or lease.get("nonce") != claim.nonce:
            claim.lost.set()
            raise LeaseExpiredError(
                f"rank {self.rank} lost its lease on range {k} "
                f"(held by rank {lease.get('rank') if lease else '?'} now)"
            )

    def release(self, k: int) -> None:
        """Drop a held lease (after commit, or on abandon)."""
        with self._lock:
            claim = self._held.pop(k, None)
        if claim is None or claim.lost.is_set():
            return
        lease = _read_json(self.lease_path(k))
        if lease is not None and lease.get("nonce") == claim.nonce:
            try:
                os.unlink(self.lease_path(k))
            except OSError:
                pass

    # -- commit ---------------------------------------------------------

    def commit(self, k: int, payload: dict) -> bool:
        """Exactly-once range commit: ``os.link`` the marker into place.
        Returns False when another rank already committed ``k`` (the
        double-commit race — both produced byte-identical parts, only
        the first marker counts)."""
        body = {
            "schema": DONE_SCHEMA, "range": k, "rank": self.rank,
            "committed": time.time(), **payload,
        }
        tmp = os.path.join(
            self.root, "done",
            f".commit.{k:05d}.{self.rank}.{uuid.uuid4().hex[:8]}",
        )
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(body, fh)
            fh.write("\n")
        try:
            os.link(tmp, self.done_path(k))
        except OSError as e:
            os.unlink(tmp)
            if e.errno == errno.EEXIST:
                return False
            raise
        os.unlink(tmp)
        return True

    # -- heartbeats -----------------------------------------------------

    def _beat(self) -> None:
        with self._lock:
            held = sorted(self._held)
            claims = [self._held[k] for k in held]
        now = time.time()
        for claim in claims:
            # renewal = bump the lease file's MTIME (os.utime, atomic).
            # Never a content rewrite: a read-verify-replace could land
            # AFTER a stealer's fresh lease and shadow it with our
            # stale nonce.  If we lost the race between the nonce read
            # and the utime, the touch lands on the stealer's
            # just-created (already-fresh) lease — harmless — and our
            # next fence/renewal sees the foreign nonce and marks lost.
            k = claim.range.range_id
            lease = _read_json(self.lease_path(k))
            if lease is None or lease.get("nonce") != claim.nonce:
                claim.lost.set()
                continue
            try:
                os.utime(self.lease_path(k))
            except OSError:
                claim.lost.set()
        _write_atomic(
            self.heartbeat_path(),
            {
                "rank": self.rank, "pid": os.getpid(), "ts": now,
                "holding": held, "ranges_done": self.done_count(),
                "reassignments": self.reassignments,
            },
        )
        if self.journal is not None:
            self.journal.emit("heartbeat", rank=self.rank, holding=held)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except OSError as e:  # a full/flaky share must not kill the
                logger.warning(  # rank — the lease just ages toward steal
                    "rank %d heartbeat failed: %s", self.rank, e,
                )

    def rank_heartbeat_ages(self) -> dict[int, float]:
        """rank -> seconds since its last heartbeat file write — the
        live fleet view the metrics exporter samples per scrape."""
        out: dict[int, float] = {}
        hb_dir = os.path.join(self.root, "hb")
        now = time.time()
        try:
            names = os.listdir(hb_dir)
        except OSError:
            return out
        for name in sorted(names):
            if not name.startswith("rank_"):
                continue
            data = _read_json(os.path.join(hb_dir, name))
            if data is None or not isinstance(data.get("ts"), (int, float)):
                continue
            out[int(data.get("rank", name[5:10]))] = max(
                now - data["ts"], 0.0
            )
        return out

    def wait_for_work(self, timeout: float | None = None) -> None:
        """Park between claim scans; wakes early on stop()."""
        self._stop.wait(
            timeout if timeout is not None
            else min(self.heartbeat_interval, 0.5)
        )

    def stop(self) -> None:
        self._stop.set()
        self._hb_thread.join(timeout=10)
        with self._lock:
            held = list(self._held)
        for k in held:
            self.release(k)
