"""Work coordinator for elastic multi-host runs: leases, exactly-once
commits, fencing, and live work-stealing over a pluggable state store.

The static ``_shard_for_process`` partition assumes a fixed healthy rank
set.  This module replaces it with **dynamic distribution of chunk
ranges** over a small-record store — a shared directory
(:class:`~specpride_tpu.parallel.store.FsStore`) or a conditional-put
object store (:class:`~specpride_tpu.parallel.store.HttpCasStore`,
``--elastic URL``) — tier 1 (PR 9) plus tier 2's live rebalancing:

* ``plan.json`` — the deterministic work plan: ``n_clusters`` split into
  fixed cluster-index **ranges** of ``range_size``.  Every rank derives
  the identical plan from its own input parse; the first rank persists
  it (create-if-absent) and later ranks verify theirs matches, so a
  fleet run against divergent inputs fails loudly instead of merging
  garbage.
* ``leases/range_<k>.json`` — at most one rank works a range at a time.
  A claim is a create-if-absent; the holder renews by ``touch`` (an
  atomic freshness bump that can never overwrite a lease a stealer just
  re-created).  A lease whose store-side age exceeds the holder's TTL
  (plus a grace margin against clock skew) may be **stolen**: the
  observer compare-and-deletes it — only one racer wins — re-claims the
  range, and only then journals ``lease_expire`` + ``chunk_reassign``.
* ``done/range_<k>.json`` — the commit marker: create-if-absent, so two
  ranks racing the same range commit exactly once.  The marker carries
  the range part file's ``output_bytes`` + ``sha256``, which is what
  ``merge-parts --elastic`` verifies before concatenating.
* ``hb/rank_<r>.json`` — per-rank heartbeats (last-writer-wins), now
  carrying per-range progress (clusters committed, EWMA chunk wall) —
  the signal stealers use to pick the most-behind donor.
* ``split/…`` + ``overlay/…`` — the **live work-stealing** handshake
  (tier 2).  A rank with nothing claimable proposes a split of a live
  peer's range; the donor ratifies at its next chunk boundary by
  publishing a *cut* fenced to its lease nonce and registering the
  split-off tail as a new range in the plan's **overlay**; the stealer
  (or any idle rank) claims the tail like any other range.  See the
  walkthrough below.

Work-stealing handshake (all steps atomic create-if-absent, so every
race has exactly one winner):

1. **Propose** — the stealer reads the donor's live lease (nonce ``N``)
   and creates ``split/range_<k>.proposed.<N>.json``.  The nonce scopes
   the proposal to THIS holder's tenure: a proposal outlives nothing.
2. **Ratify** — the donor polls for proposals against its own nonce on
   its dispatch lane, once per chunk, BEFORE dispatching the next chunk.
   It picks the cut ``C`` = the first cluster of that not-yet-submitted
   chunk (so every chunk already committed or in flight through the
   ordered write lane stays strictly below ``C``), registers the tail
   ``[C, stop)`` as overlay range ``K'``, publishes
   ``split/range_<k>.cut.<N>.json`` = ``{cut, new_range}``, journals
   ``lease_split``, and stops dispatching — its range is now
   ``[start, C)``.
3. **Fence** — the donor's commit fence refuses any commit at or past
   ``C`` with :class:`LeaseExpiredError` (permanent), so even a zombie
   donor that never saw its own cut cannot race the tail's new owner.
4. **Claim** — the stealer (or any rank scanning the overlay) claims
   ``K'`` under an ordinary lease and journals ``chunk_reassign``
   (paired with the donor's ``lease_split`` by the journal audit).
   The tail's part file is ``<output>.part<K'>``; ``merge-parts``
   orders parts by cluster START, so the merged bytes stay identical
   to a single-host serial run.

Fencing: the holder's lease carries a per-claim ``nonce``.  Before each
chunk commit the executor calls :meth:`Coordinator.commit_fence`; a
missing lease, a foreign nonce, or a commit past a ratified cut raises
:class:`~specpride_tpu.robustness.errors.LeaseExpiredError` (permanent —
never retried).  The window between the check and the append is the
residual risk; the commit-marker create and the merge-time sha256
verification catch anything that slips through, loudly.

This module is deliberately jax-free: the coordinator runs identically
on a login node, a CI box, or a TPU host.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import uuid

from specpride_tpu.observability.journal import emit_clock_anchor
from specpride_tpu.observability.stats import logger
from specpride_tpu.parallel.store import (
    FsStore,
    Store,
    is_remote_spec,
    store_from_spec,
)
from specpride_tpu.robustness import faults as rb_faults
from specpride_tpu.robustness.errors import LeaseExpiredError

PLAN_SCHEMA = 1
DONE_SCHEMA = 1

# default lease time-to-live and the grace margin an observer adds on
# top before declaring a lease dead (absorbs clock skew between hosts
# sharing a filesystem; the object-store backend judges age with the
# SERVER's clock, where the same grace covers network latency instead)
DEFAULT_TTL_S = 10.0
DEFAULT_GRACE_FRAC = 0.5

# a split leaves the donor at least this many of its own chunks, and a
# proposal targets only ranges with at least twice this much estimated
# work left — stealing a nearly-done range would buy nothing but churn
MIN_DONOR_CHUNKS = 1

# EWMA smoothing for the per-chunk wall the heartbeat publishes (the
# journal's chunk_done.elapsed_s is the same quantity, measured at the
# same commit; the heartbeat mirror exists because peers cannot read
# each other's journals without a shared filesystem)
_EWMA_ALPHA = 0.3


@dataclasses.dataclass(frozen=True)
class ChunkRange:
    """One unit of claimable work: a contiguous block of cluster
    indices.  Base ranges are fixed by the plan; **overlay** ranges
    (``parent`` set) are split-off tails registered by the stealing
    handshake — either way, every rank and every post-mortem resolves
    range ``k`` to the same clusters and the same ``.part<k>``
    output."""

    range_id: int
    start: int
    stop: int
    parent: int | None = None
    from_rank: int | None = None

    @property
    def n_clusters(self) -> int:
        return self.stop - self.start


@dataclasses.dataclass
class Claim:
    """A held lease on one range."""

    range: ChunkRange
    nonce: str
    takeover: bool = False
    from_rank: int | None = None
    lost: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )


def plan_ranges(n_clusters: int, range_size: int) -> list[ChunkRange]:
    """The deterministic plan: ``n_clusters`` in blocks of
    ``range_size``.  An empty input still plans ONE empty range so the
    claimer writes an empty part and ``merge-parts`` finds something."""
    size = max(int(range_size), 1)
    if n_clusters <= 0:
        return [ChunkRange(0, 0, 0)]
    return [
        ChunkRange(k, start, min(start + size, n_clusters))
        for k, start in enumerate(range(0, n_clusters, size))
    ]


class Coordinator:
    """One rank's handle on the shared elastic work queue.

    Construction registers the plan (or verifies it against the one a
    peer already wrote) and starts the heartbeat thread; callers MUST
    pair with :meth:`stop` (the CLI does so in a ``finally``).

    ``root`` is the ``--elastic`` spec: a shared directory or an
    ``http(s)://`` object-store URL.  ``local_dir`` holds the per-range
    resume manifests (``ck/``) — they are ordinary checkpoint files the
    executor replaces atomically, so they stay on a filesystem even
    when coordination state lives in an object store (defaults to the
    store directory itself on the filesystem backend)."""

    def __init__(
        self,
        root: str,
        rank: int,
        n_clusters: int,
        range_size: int,
        ttl: float = DEFAULT_TTL_S,
        heartbeat_interval: float = 0.0,
        journal=None,
        local_dir: str | None = None,
        steal: bool = True,
        chunk_hint: int = 0,
        trace: str | None = None,
    ):
        self.root = root
        self.store: Store = store_from_spec(root)
        if local_dir is None:
            if is_remote_spec(root):
                raise ValueError(
                    "an object-store coordinator needs local_dir for its "
                    "per-range resume manifests"
                )
            local_dir = self.store.root  # type: ignore[attr-defined]
        self.local_dir = os.path.abspath(local_dir)
        self.rank = int(rank)
        self.ttl = max(float(ttl), 0.1)
        self.grace = self.ttl * DEFAULT_GRACE_FRAC
        self.heartbeat_interval = (
            float(heartbeat_interval)
            if heartbeat_interval and heartbeat_interval > 0
            else max(self.ttl / 4.0, 0.05)
        )
        self.journal = journal
        # the run's trace-context handoff ("trace_id:span_id"): the plan
        # creator registers it so LATE-JOINING ranks (spares spawned
        # without the SPECPRIDE_TRACE env) adopt the same trace instead
        # of minting their own — one elastic run, one causal timeline
        self.trace = trace
        self.steal_enabled = bool(steal)
        self.chunk_hint = max(int(chunk_hint), 1)
        self.n_clusters = int(n_clusters)
        self.range_size = max(int(range_size), 1)
        base = plan_ranges(n_clusters, range_size)
        self.n_base_ranges = len(base)
        self._by_id: dict[int, ChunkRange] = {
            r.range_id: r for r in base
        }
        # observed-recovery counters the liveness exporter mirrors
        self.lease_expires_observed = 0
        self.reassignments = 0
        self.ranges_run = 0
        self.lease_splits = 0  # splits this rank ratified as donor
        self.steals = 0  # overlay tails this rank claimed
        self.cas_conflicts = 0
        self._lock = threading.Lock()
        self._held: dict[int, Claim] = {}
        self._cuts: dict[int, int] = {}  # range -> ratified cut (global)
        # autotune's --elastic-range actuator (ROADMAP item 4b): caps
        # how many clusters a donor cedes per ratified split.  None =
        # classic steal-half, byte parity with pre-autotune behavior.
        self._split_hint: int | None = None
        self._progress: dict[int, dict] = {}  # range -> {done, chunk_s}
        # rank-level EWMA chunk wall: what the journal heartbeat's
        # chunk_s (v5) carries.  Deliberately NOT the held-range view
        # above (which empties the moment a range commits): the rank's
        # measured pace outlives any one range, and the autotune
        # elastic policy reads it at drain time, after the last commit
        self._chunk_s_ewma: float | None = None
        self._done_cache: set[int] = set()  # commit markers never vanish
        self._stop = threading.Event()
        os.makedirs(os.path.join(self.local_dir, "ck"), exist_ok=True)
        # register this identity even when --process-id pinned it, so a
        # later auto-assigning joiner (a fleet-spawned spare) can never
        # collide with an explicitly numbered rank's journals/heartbeats
        self.store.put_new(
            f"ranks/rank_{self.rank:05d}", {"pid": os.getpid()}
        )
        self._register_plan()
        # one immediate beat before the loop: every rank's journal holds
        # at least one heartbeat (the stats rank view keys off it) and
        # the exporter's age gauge starts near zero, even on runs that
        # finish inside the first interval
        self._beat()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"specpride-heartbeat-r{self.rank}", daemon=True,
        )
        self._hb_thread.start()

    @property
    def ranges(self) -> list[ChunkRange]:
        """The live range table (base plan + discovered overlays, cuts
        applied), in id order."""
        with self._lock:
            return [self._by_id[k] for k in sorted(self._by_id)]

    # -- plan -----------------------------------------------------------

    def _plan_payload(self) -> dict:
        return {
            "schema": PLAN_SCHEMA,
            "n_clusters": self.n_clusters,
            "range_size": self.range_size,
            "n_ranges": self.n_base_ranges,
            **({"trace": self.trace} if self.trace else {}),
        }

    def _register_plan(self) -> None:
        payload = self._plan_payload()
        self.store.put_new("plan.json", payload)
        existing = self.store.get("plan.json")
        if existing is None:
            raise SystemExit(
                f"elastic plan under {self.store.describe()} is "
                "unreadable — another rank wrote a torn plan or the "
                "store is not shared between ranks"
            )
        for key in ("n_clusters", "range_size"):
            if existing[0].get(key) != payload[key]:
                raise SystemExit(
                    f"elastic plan mismatch ({self.store.describe()}): "
                    f"this rank derived {key}={payload[key]} but the "
                    f"registered plan says {existing[0].get(key)} — are "
                    "all ranks running the same input and "
                    "--elastic-range?"
                )

    @classmethod
    def read_plan(cls, root: str) -> dict | None:
        """The registered plan, for ``merge-parts --elastic`` and the
        stats/exporter consumers (None when absent/unreadable)."""
        got = store_from_spec(root).get("plan.json")
        return got[0] if got is not None else None

    # -- rank identity --------------------------------------------------

    @staticmethod
    def assign_rank(root: str, limit: int = 4096) -> int:
        """Auto-assign the lowest free rank id via create-if-absent
        marker records — used when ``--process-id`` is not given.  Ranks
        are identities, not a partition: any number may join or
        rejoin."""
        store = store_from_spec(root)
        for r in range(limit):
            if store.put_new(f"ranks/rank_{r:05d}", {"pid": os.getpid()}):
                return r
        raise SystemExit(f"no free rank id under {root}")

    # -- keys / paths ---------------------------------------------------

    def _lease_key(self, k: int) -> str:
        return f"leases/range_{k:05d}.json"

    def _done_key(self, k: int) -> str:
        return f"done/range_{k:05d}.json"

    def _proposal_key(self, k: int, nonce: str) -> str:
        return f"split/range_{k:05d}.proposed.{nonce}.json"

    def _cut_key(self, k: int, nonce: str) -> str:
        return f"split/range_{k:05d}.cut.{nonce}.json"

    def _overlay_key(self, k: int) -> str:
        return f"overlay/range_{k:05d}.json"

    def _store_root(self) -> str:
        """The coordinator-state directory (FsStore only — these path
        helpers exist for tests/post-mortems that poke records
        directly; coordination records live in the STORE, which with
        an object-store backend has no filesystem path at all)."""
        if not isinstance(self.store, FsStore):
            raise ValueError(
                f"{self.store.describe()} keeps coordinator records "
                "server-side; there is no filesystem path to them"
            )
        return self.store.root

    def lease_path(self, k: int) -> str:
        return os.path.join(
            self._store_root(), "leases", f"range_{k:05d}.json"
        )

    def done_path(self, k: int) -> str:
        return os.path.join(
            self._store_root(), "done", f"range_{k:05d}.json"
        )

    def checkpoint_path(self, k: int) -> str:
        """The per-range resume manifest — coordinator-owned so elastic
        runs are ALWAYS checkpointed (reassignment needs the manifest to
        know which chunks the dead rank committed).  Always a local
        filesystem path: the executor replaces it atomically per
        chunk."""
        return os.path.join(self.local_dir, "ck", f"range_{k:05d}.json")

    def heartbeat_path(self, rank: int | None = None) -> str:
        r = self.rank if rank is None else rank
        return os.path.join(
            self._store_root(), "hb", f"rank_{r:05d}.json"
        )

    # -- range table ----------------------------------------------------

    def _refresh_ranges(self) -> None:
        """Fold ratified cuts published by peers into the local range
        table.  The CUT record is the single atomic source of truth for
        a split — it names the overlay id and carries the tail's full
        extent — so a donor that dies between allocating an overlay id
        and publishing the cut leaves only invisible allocation debris:
        the parent stays whole, its takeover recomputes the full range,
        and no duplicate tail can ever be claimed.  Cut records are
        immutable once written, so this only ever adds entries or
        narrows stops."""
        for key in self.store.list_keys("split/"):
            if ".cut." not in key:
                continue
            got = self.store.get(key)
            if got is None:
                continue
            rec = got[0]
            try:
                parent = int(key.rsplit("/", 1)[1].split(".", 1)[0]
                             .replace("range_", ""))
            except ValueError:
                continue
            cut = rec.get("cut")
            if not isinstance(cut, int):
                continue
            rid = rec.get("new_range")
            if isinstance(rid, int):
                with self._lock:
                    if rid not in self._by_id:
                        self._by_id[rid] = ChunkRange(
                            rid, cut, int(rec.get("stop", cut)),
                            parent=parent,
                            from_rank=rec.get("donor_rank"),
                        )
            self._apply_cut(parent, cut)

    def _apply_cut(self, parent: int, cut: int) -> None:
        with self._lock:
            rng = self._by_id.get(parent)
            if rng is None or cut >= rng.stop:
                return
            self._by_id[parent] = dataclasses.replace(rng, stop=cut)
            prev = self._cuts.get(parent)
            self._cuts[parent] = cut if prev is None else min(prev, cut)

    def effective_range(self, k: int) -> ChunkRange:
        """Range ``k``'s current extent — narrowed by any ratified
        cut."""
        with self._lock:
            return self._by_id[k]

    # -- leases ---------------------------------------------------------

    def _is_done(self, k: int) -> bool:
        if k in self._done_cache:
            return True
        if self.store.get(self._done_key(k)) is not None:
            self._done_cache.add(k)
            return True
        return False

    def _lease_payload(self, nonce: str) -> dict:
        return {
            "rank": self.rank,
            "pid": os.getpid(),
            "nonce": nonce,
            "claimed": time.time(),
            "ttl": self.ttl,
        }

    def _lease_expired(
        self, k: int, lease: dict, age: float | None = None
    ) -> tuple[bool, float]:
        """(expired?, seconds past deadline) judged from the lease's
        store-side age — seconds since the holder's last renewal as the
        STORE's clock saw it — plus the holder's declared TTL and the
        clock-skew grace.  Callers that just read the lease pass the
        age from the same round trip."""
        if age is None:
            age = self.store.age_s(self._lease_key(k))
        if age is None:
            return False, 0.0  # mid-steal — look again next scan
        ttl = lease.get("ttl")
        if not isinstance(ttl, (int, float)) or ttl <= 0:
            ttl = self.ttl
        over = age - (ttl + self.grace)
        return over > 0, max(over, 0.0)

    def _remaining_clusters(self, rng: ChunkRange) -> int:
        """Clusters of ``rng`` NOT yet committed in its checkpoint
        manifest — the chunk_reassign payload's honest remainder."""
        import json as _json

        try:
            with open(self.checkpoint_path(rng.range_id),
                      encoding="utf-8") as fh:
                manifest = _json.load(fh)
        except (OSError, ValueError):
            return rng.n_clusters
        if not isinstance(manifest, dict):
            return rng.n_clusters
        done = manifest.get("done")
        n_done = len(done) if isinstance(done, list) else 0
        return max(rng.n_clusters - n_done, 0)

    def _cas_conflict(self, err: Exception) -> None:
        """An injected (or, with a real object store, genuine)
        compare-and-swap conflict: lose this attempt gracefully and let
        the claim loop re-scan.  Journaled as a ``retry`` at the
        ``cas`` site so the chaos audit pairs the fault with its
        recovery."""
        with self._lock:
            # the claim loop and the heartbeat lane's renewals can both
            # lose a CAS race; unguarded += would drop conflicts
            self.cas_conflicts += 1
        if self.journal is not None:
            self.journal.emit(
                "retry", site="cas", attempt=0, backoff_s=0.0,
                error=f"{type(err).__name__}: {err}",
            )
        logger.warning(
            "rank %d: coordinator CAS conflict (%s); re-scanning",
            self.rank, err,
        )

    def _try_claim(self, rng: ChunkRange) -> Claim | None:
        k = rng.range_id
        nonce = uuid.uuid4().hex
        try:
            rb_faults.check("cas")
        except rb_faults.InjectedCasConflict as e:
            self._cas_conflict(e)
            return None
        if self.store.put_new(self._lease_key(k), self._lease_payload(nonce)):
            claim = Claim(rng, nonce)
            if os.path.exists(self.checkpoint_path(k)):
                # a prior holder died after its lease was cleaned up (or
                # released without committing): partial state exists, so
                # this fresh-looking claim is still a takeover
                claim.takeover = True
            self._note_claim(claim)
            return claim
        got = self.store.get_with_age(self._lease_key(k))
        if got is None:
            return None  # torn or mid-steal — look again next scan
        lease, etag, age = got
        # (a dead previous incarnation of THIS rank id is handled like
        # any other dead rank: its lease simply ages out below)
        expired, over_s = self._lease_expired(k, lease, age)
        if not expired:
            return None  # live holder
        # expired: steal via compare-and-delete — only one racer wins
        if not self.store.delete_if(self._lease_key(k), etag):
            return None  # lost the steal race
        dead_rank = lease.get("rank", -1)
        if not self.store.put_new(
            self._lease_key(k), self._lease_payload(nonce)
        ):
            # another claimer slipped into the gap between our delete
            # and our create: ITS lease_claim covers the range, so emit
            # NOTHING here — a lease_expire with no paired
            # chunk_reassign would fail the audit over zero lost work
            return None
        with self._lock:
            self.lease_expires_observed += 1
            self.reassignments += 1
        if self.journal is not None:
            self.journal.emit(
                "lease_expire", rank=dead_rank, range=k,
                observed_by=self.rank, expired_for_s=round(over_s, 3),
            )
        logger.warning(
            "rank %d: lease on range %d held by rank %s expired; "
            "reassigning", self.rank, k, dead_rank,
        )
        claim = Claim(rng, nonce, takeover=True, from_rank=dead_rank)
        if self.journal is not None:
            self.journal.emit(
                "chunk_reassign", range=k, from_rank=dead_rank,
                to_rank=self.rank,
                n_clusters_remaining=self._remaining_clusters(rng),
            )
        self._note_claim(claim)
        return claim

    def _note_claim(self, claim: Claim) -> None:
        k = claim.range.range_id
        with self._lock:
            self._held[k] = claim
            self.ranges_run += 1
        if self.journal is not None:
            self.journal.emit(
                "lease_claim", rank=self.rank, range=k,
                takeover=claim.takeover,
                **(
                    {"from_rank": claim.from_rank}
                    if claim.from_rank is not None else {}
                ),
            )
        if claim.range.parent is not None and not claim.takeover:
            # first claim of a split-off tail: THIS is the reassignment
            # that pairs with the donor's lease_split in the audit —
            # whoever wins the claim (the proposing stealer usually,
            # any idle rank legitimately) emits it
            with self._lock:
                self.steals += 1
            if self.journal is not None:
                self.journal.emit(
                    "chunk_reassign", range=k,
                    from_rank=claim.range.from_rank
                    if claim.range.from_rank is not None else -1,
                    to_rank=self.rank,
                    n_clusters_remaining=claim.range.n_clusters,
                    via="lease_split",
                )

    def _holds(self, k: int) -> bool:
        with self._lock:
            return k in self._held

    def claim_next(self) -> Claim | None:
        """Claim the next available range, scanning from this rank's own
        offset (ranks start at different ranges, so a healthy fleet
        claims disjoint work without ever contending).  None = nothing
        claimable right now (all done, or every open range is leased by
        a live rank — try a steal, then poll again)."""
        self._refresh_ranges()
        ranges = self.ranges
        n = len(ranges)
        for i in range(n):
            rng = ranges[(self.rank + i) % n]
            if rng.n_clusters <= 0 and rng.parent is not None:
                continue  # voided overlay (cut == stop)
            if self._is_done(rng.range_id):
                continue
            if rng.from_rank == self.rank and rng.parent is not None:
                # our own split-off tail: the whole point of the split
                # was to move this work OFF this (slow) rank, and the
                # stealer that asked is microseconds behind us — defer
                # until the tail has gone unclaimed for a full expiry
                # window (the stealer died), then pick it up after all
                age = self.store.age_s(self._overlay_key(rng.range_id))
                if age is not None and age < self.ttl + self.grace:
                    continue
            claim = self._try_claim(rng)
            if claim is not None:
                return claim
        return None

    def all_committed(self) -> bool:
        self._refresh_ranges()
        return all(
            self._is_done(r.range_id)
            for r in self.ranges
            if r.n_clusters > 0 or r.parent is None
        )

    def done_count(self) -> int:
        return sum(self._is_done(r.range_id) for r in self.ranges)

    def counters(self) -> dict:
        """This rank's lease-state counters as one dict — the view the
        run summary records in ``stats.elastic`` and the flight
        recorder snapshots into every incident bundle (``host.json``):
        store-derived state a dead rank's journal alone cannot
        reconstruct."""
        return {
            "ranges_run": self.ranges_run,
            "ranges_committed": self.done_count(),
            "lease_expires_observed": self.lease_expires_observed,
            "reassignments": self.reassignments,
            "lease_splits": self.lease_splits,
            "steals": self.steals,
            "cas_conflicts": self.cas_conflicts,
        }

    # -- work-stealing (tier 2) -----------------------------------------

    def _steal_candidates(self) -> list[tuple[float, ChunkRange, dict]]:
        """Open, live-leased ranges worth splitting, best target first.

        Score = estimated seconds of work left on the range, from the
        holder's heartbeat progress mirror (clusters committed + EWMA
        chunk wall — the same per-chunk timings the journal's
        ``chunk_done`` events carry).  Ranges without progress data
        score by remaining clusters alone."""
        progress_by_rank: dict[int, dict] = {}
        for key in self.store.list_keys("hb/"):
            got = self.store.get(key)
            if got is None:
                continue
            hb = got[0]
            if isinstance(hb.get("rank"), int):
                progress_by_rank[hb["rank"]] = hb.get("progress") or {}
        out: list[tuple[float, ChunkRange, dict]] = []
        for rng in self.ranges:
            k = rng.range_id
            if self._is_done(k) or self._holds(k):
                continue
            got = self.store.get_with_age(self._lease_key(k))
            if got is None:
                continue
            lease, _, age = got
            expired, _ = self._lease_expired(k, lease, age)
            if expired:
                continue  # the expiry path owns dead leases
            prog = progress_by_rank.get(lease.get("rank", -1), {}).get(
                str(k), {}
            )
            done = int(prog.get("done", 0) or 0)
            remaining = max(rng.n_clusters - done, 0)
            if remaining < 2 * max(self.chunk_hint, 1):
                continue  # too little left to be worth a handshake
            chunk_s = prog.get("chunk_s")
            per_cluster = (
                float(chunk_s) / max(self.chunk_hint, 1)
                if isinstance(chunk_s, (int, float)) and chunk_s > 0
                else 1.0
            )
            out.append((remaining * per_cluster, rng, lease))
        out.sort(key=lambda t: -t[0])
        return out

    def try_steal(self, poll_timeout: float | None = None) -> Claim | None:
        """Attempt one live steal: propose a split of the most-loaded
        live peer's range, wait for the donor to ratify a cut at its
        next chunk boundary, and claim the split-off tail.  None =
        nothing stealable (no live target with enough work, the donor
        finished first, or another rank won the tail)."""
        if not self.steal_enabled:
            return None
        self._refresh_ranges()
        candidates = self._steal_candidates()
        if not candidates:
            return None
        timeout = (
            float(poll_timeout) if poll_timeout is not None
            else min(2.0 * self.heartbeat_interval + 0.5, self.ttl)
        )
        for _, rng, lease in candidates[:2]:
            claim = self._steal_one(rng, lease, timeout)
            if claim is not None:
                return claim
        return None

    def _steal_one(
        self, rng: ChunkRange, lease: dict, timeout: float
    ) -> Claim | None:
        k = rng.range_id
        nonce = lease.get("nonce")
        if not isinstance(nonce, str):
            return None
        try:
            rb_faults.check("cas")
        except rb_faults.InjectedCasConflict as e:
            self._cas_conflict(e)
            return None
        # propose (idempotent: a racing proposer's record serves the
        # same purpose — we poll the cut either way)
        self.store.put_new(
            self._proposal_key(k, nonce),
            {"parent": k, "donor_rank": lease.get("rank", -1),
             "stealer_rank": self.rank, "donor_nonce": nonce},
        )
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline and not self._stop.is_set():
            got = self.store.get(self._cut_key(k, nonce))
            if got is not None:
                rec = got[0]
                new_id = rec.get("new_range")
                if not isinstance(new_id, int):
                    return None  # donor declined (nothing left to give)
                cut = int(rec.get("cut", rng.stop))
                self._apply_cut(k, cut)
                self._refresh_ranges()
                with self._lock:
                    tail = self._by_id.get(new_id)
                if tail is None or tail.n_clusters <= 0:
                    return None
                return self._try_claim(tail)
            if self._is_done(k):
                return None  # donor finished the whole range first
            current = self.store.get(self._lease_key(k))
            if current is None or current[0].get("nonce") != nonce:
                return None  # donor died/released — the expiry path owns it
            self._stop.wait(min(0.05, timeout / 4.0))
        return None

    # -- donor side: ratify + clip + fence ------------------------------

    def _allocate_overlay(self, start: int, stop: int, parent: int) -> int:
        """Mint a fresh range id for the split-off tail (create-if-
        absent id allocation — two concurrent splits can never take the
        same id).  The overlay record is ONLY the allocation marker:
        peers learn the tail's existence and extent from the cut record
        that references it, so an id allocated by a donor that died
        before publishing its cut is harmless debris."""
        with self._lock:
            rid = max(
                [self.n_base_ranges] + [k + 1 for k in self._by_id]
            )
        while True:
            rec = {
                "range_id": rid, "start": start, "stop": stop,
                "parent": parent, "from_rank": self.rank,
            }
            if self.store.put_new(self._overlay_key(rid), rec):
                with self._lock:
                    self._by_id[rid] = ChunkRange(
                        rid, start, stop, parent=parent,
                        from_rank=self.rank,
                    )
                return rid
            rid += 1

    def clip_or_ratify(self, k: int, next_min_idx: int) -> int | None:
        """The donor's per-chunk dispatch-lane hook, called BEFORE
        submitting the chunk whose first local cluster index is
        ``next_min_idx``.  Returns the LOCAL clip index (stop before it)
        when this range has been split, else None.

        Ratification happens here — on the lane that knows the
        submission frontier — so the cut always lands at the boundary
        of a chunk that has NOT been handed to the ordered write lane:
        everything already in flight commits strictly below the cut and
        the commit fence never fires on the donor's own queued work."""
        with self._lock:
            claim = self._held.get(k)
            if claim is None or claim.lost.is_set():
                return None
            rng = self._by_id[k]
            cut = self._cuts.get(k)
            hint = self._split_hint
        if cut is not None:
            return max(cut - rng.start, 0)
        if not self.steal_enabled or next_min_idx <= 0:
            # the donor always keeps at least its first chunk: a zero
            # cut would leave an empty committed range behind
            return None
        if self.store.get(self._proposal_key(k, claim.nonce)) is None:
            return None
        # steal-half: the donor keeps the first half of its remaining
        # work (whole chunks, at least one) and cedes the rest.  Ceding
        # everything past the next boundary would leave a slow donor
        # idle one chunk later, stealing back from the stealer — the
        # classic halving policy converges geometrically instead.  The
        # cut can never land below the submission frontier: everything
        # up to ``next_min_idx`` is already in flight and commits below
        # it by construction.
        chunk = max(self.chunk_hint, 1)
        remaining = rng.stop - (rng.start + int(next_min_idx))
        keep = max((remaining // 2) // chunk, 1) * chunk
        if hint:
            # autotune cap: cede at most ~hint clusters (whole chunks,
            # at least one) so split-off tails land near the tuned
            # range size.  Only ever GROWS keep — the donor's committed
            # frontier and byte parity are untouched either way.
            cede = max(int(hint) // chunk, 1) * chunk
            keep = max(keep, remaining - cede)
        cut_global = rng.start + int(next_min_idx) + keep
        if cut_global >= rng.stop:
            # nothing left to give: publish a declined cut so the
            # stealer's poll terminates instead of timing out
            self.store.put_new(
                self._cut_key(k, claim.nonce),
                {"cut": rng.stop, "new_range": None},
            )
            with self._lock:
                self._cuts[k] = rng.stop
            return None
        new_id = self._allocate_overlay(cut_global, rng.stop, k)
        # the ONE atomic publication of the split: everything a peer
        # needs to claim the tail (id, extent, donor) rides the cut
        self.store.put_new(
            self._cut_key(k, claim.nonce),
            {"cut": cut_global, "new_range": new_id, "stop": rng.stop,
             "parent": k, "donor_rank": self.rank},
        )
        self._apply_cut(k, cut_global)
        with self._lock:
            self.lease_splits += 1
        if self.journal is not None:
            self.journal.emit(
                "lease_split", range=k, new_range=new_id,
                rank=self.rank, split_at=cut_global,
                n_clusters_split=rng.stop - cut_global,
            )
        logger.info(
            "rank %d: split range %d at cluster %d — tail of %d "
            "clusters is now range %d", self.rank, k, cut_global,
            rng.stop - cut_global, new_id,
        )
        return max(cut_global - rng.start, 0)

    @property
    def split_hint(self) -> int | None:
        with self._lock:
            return self._split_hint

    def set_split_hint(self, n: int | None) -> None:
        """Autotune's ``elastic_range`` actuator: future ratified splits
        cede at most ~``n`` clusters (rounded to whole chunks).  Applies
        only to ranges not yet cut — never resizes claimed work, so
        output byte parity is untouched.  ``None`` restores steal-half."""
        with self._lock:
            self._split_hint = int(n) if n else None

    def check_lease(self, k: int) -> None:
        """The basic fence: raise :class:`LeaseExpiredError` when this
        rank no longer holds range ``k`` — the lease record is gone
        (stolen) or carries a foreign nonce (stolen and re-claimed)."""
        with self._lock:
            claim = self._held.get(k)
        if claim is None or claim.lost.is_set():
            raise LeaseExpiredError(
                f"rank {self.rank} lost its lease on range {k}"
            )
        got = self.store.get(self._lease_key(k))
        lease = got[0] if got is not None else None
        if lease is None or lease.get("nonce") != claim.nonce:
            claim.lost.set()
            raise LeaseExpiredError(
                f"rank {self.rank} lost its lease on range {k} "
                f"(held by rank {lease.get('rank') if lease else '?'} now)"
            )

    def commit_fence(self, k: int, max_idx: int | None = None,
                     n_clusters: int = 0,
                     chunk_t0: float | None = None) -> None:
        """The per-commit fence the executor calls before any bytes
        land: :meth:`check_lease` plus the split fence — a commit
        whose chunk reaches at or past a ratified cut raises
        :class:`LeaseExpiredError`, so a donor that somehow kept
        dispatching past its cut (a zombie that never ran the clip)
        abandons instead of racing the tail's new owner.  Also folds
        this chunk into the progress mirror the heartbeat publishes."""
        self.check_lease(k)
        with self._lock:
            rng = self._by_id[k]
            cut = self._cuts.get(k)
            if n_clusters > 0:
                prog = self._progress.setdefault(
                    k, {"done": 0, "chunk_s": None}
                )
                prog["done"] = int(prog["done"]) + int(n_clusters)
                if chunk_t0 is not None:
                    dt = max(time.perf_counter() - chunk_t0, 0.0)
                    prev = prog["chunk_s"]
                    prog["chunk_s"] = (
                        dt if prev is None
                        else _EWMA_ALPHA * dt + (1 - _EWMA_ALPHA) * prev
                    )
                    self._chunk_s_ewma = (
                        dt if self._chunk_s_ewma is None
                        else _EWMA_ALPHA * dt
                        + (1 - _EWMA_ALPHA) * self._chunk_s_ewma
                    )
        if (
            cut is not None and max_idx is not None
            and rng.start + int(max_idx) >= cut
        ):
            raise LeaseExpiredError(
                f"rank {self.rank}: range {k} was split at cluster "
                f"{cut}; the suffix belongs to the stealing rank now"
            )

    def release(self, k: int) -> None:
        """Drop a held lease (after commit, or on abandon)."""
        with self._lock:
            claim = self._held.pop(k, None)
        if claim is None or claim.lost.is_set():
            return
        got = self.store.get(self._lease_key(k))
        if got is not None and got[0].get("nonce") == claim.nonce:
            self.store.delete(self._lease_key(k))

    # -- commit ---------------------------------------------------------

    def commit(self, k: int, payload: dict) -> bool:
        """Exactly-once range commit: create-if-absent marker.  Returns
        False when another rank already committed ``k`` (the
        double-commit race — both produced byte-identical parts, only
        the first marker counts)."""
        body = {
            "schema": DONE_SCHEMA, "range": k, "rank": self.rank,
            "committed": time.time(), **payload,
        }
        ok = self.store.put_new(self._done_key(k), body)
        if ok:
            self._done_cache.add(k)
        return ok

    # -- heartbeats -----------------------------------------------------

    def _beat(self) -> None:
        with self._lock:
            held = sorted(self._held)
            claims = [self._held[k] for k in held]
            progress = {
                str(k): {
                    "done": int(p.get("done", 0)),
                    **(
                        {"chunk_s": round(p["chunk_s"], 4)}
                        if isinstance(p.get("chunk_s"), (int, float))
                        else {}
                    ),
                }
                for k, p in self._progress.items()
                if k in self._held
            }
            chunk_s_ewma = self._chunk_s_ewma
        for claim, k in zip(claims, held):
            # renewal = an atomic freshness bump (utime on the
            # filesystem, ETag-guarded rewrite on the object store).
            # Never a blind content rewrite: that could land AFTER a
            # stealer's fresh lease and shadow it with our stale nonce.
            got = self.store.get(self._lease_key(k))
            if got is None or got[0].get("nonce") != claim.nonce:
                claim.lost.set()
                continue
            if not self.store.touch(self._lease_key(k)):
                claim.lost.set()
        self.store.put(
            f"hb/rank_{self.rank:05d}.json",
            {
                "rank": self.rank, "pid": os.getpid(),
                "ts": time.time(), "holding": held,
                "ranges_done": len(self._done_cache),
                "reassignments": self.reassignments,
                "ttl": self.ttl,
                "progress": progress,
            },
        )
        if self.journal is not None:
            # chunk_s (v5): this rank's EWMA chunk wall — the autotune
            # signal fold's elastic-plane input.  The RANK-level EWMA,
            # not the held-range progress view above: that view empties
            # at every range commit, and the policy must still see the
            # measured pace at the end-of-run drain tick
            self.journal.emit(
                "heartbeat", rank=self.rank, holding=held, ttl=self.ttl,
                chunk_s=(
                    round(chunk_s_ewma, 4)
                    if chunk_s_ewma is not None else None
                ),
            )
            # the clock anchor rides the heartbeat cadence: a long
            # elastic run's journal stays wall-alignable (bounded skew)
            # even across NTP slews mid-run
            emit_clock_anchor(self.journal)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except OSError as e:  # a full/flaky share or a store outage
                logger.warning(  # must not kill the rank — the lease
                    "rank %d heartbeat failed: %s", self.rank, e,
                )  # just ages toward steal

    def rank_heartbeat_ages(self) -> dict[int, float]:
        """rank -> seconds since its last heartbeat write (store clock)
        — the live fleet view the metrics exporter samples per
        scrape."""
        return {
            rank: age
            for rank, (age, _stopped) in self.rank_heartbeat_states().items()
        }

    def rank_heartbeat_states(self) -> dict[int, tuple[float, bool]]:
        """rank -> (age_s, stopped): the ages plus the clean-shutdown
        marker ``stop()`` writes — consumers distinguishing "finished
        and left" (stale age is fine) from "went silent mid-run"
        (presumed dead) must read this, not the bare ages (the
        ``/healthz`` readiness probe does)."""
        out: dict[int, tuple[float, bool]] = {}
        for key in self.store.list_keys("hb/"):
            got = self.store.get_with_age(key)
            if got is None:
                continue
            rank, age = got[0].get("rank"), got[2]
            if isinstance(rank, int) and age is not None:
                out[rank] = (age, bool(got[0].get("stopped")))
        return out

    def wait_for_work(self, timeout: float | None = None) -> None:
        """Park between claim scans; wakes early on stop()."""
        self._stop.wait(
            timeout if timeout is not None
            else min(self.heartbeat_interval, 0.5)
        )

    def flush_progress(self) -> None:
        """Publish one immediate heartbeat (store mirror + journal
        event) with the current progress view.  A rank that finishes
        its whole workload inside one heartbeat interval never reaches
        a timed beat with chunk walls folded in — a caller about to
        evaluate the journal's heartbeat signal (the autotune drain
        tick) asks for the final EWMA explicitly."""
        try:
            self._beat()
        except OSError as e:
            logger.warning(
                "rank %d flush heartbeat failed: %s", self.rank, e,
            )

    def stop(self) -> None:
        self._stop.set()
        self._hb_thread.join(timeout=10)
        with self._lock:
            held = list(self._held)
        for k in held:
            self.release(k)
        try:
            # a final heartbeat marked `stopped`: the fleet supervisor
            # distinguishes "this rank finished and left" (stale age is
            # fine) from "this rank went silent mid-run" (presumed dead
            # — warm a spare).  A SIGKILLed rank never writes it.
            self.store.put(
                f"hb/rank_{self.rank:05d}.json",
                {
                    "rank": self.rank, "pid": os.getpid(),
                    "ts": time.time(), "holding": [],
                    "ranges_done": len(self._done_cache),
                    "reassignments": self.reassignments,
                    "ttl": self.ttl, "progress": {}, "stopped": True,
                },
            )
        except OSError:
            pass
        self.store.close()
