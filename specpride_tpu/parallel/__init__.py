"""Device-mesh parallelism: shard the cluster axis across TPU chips/hosts.

The reference is single-threaded Python (survey §2 "Parallelism: none");
the workload is embarrassingly parallel across clusters, so the scale-out
design is: one 1-D ``jax.sharding.Mesh`` over all devices, every batched
kernel input sharded along its leading (cluster) axis, XLA SPMD-partitions
the vmapped programs with zero cross-device communication in the hot loop,
and the only collectives are the output all-gather and a final metrics
all-reduce (survey §2 / BASELINE.json config 5).
"""

from specpride_tpu.parallel.mesh import (
    CLUSTER_AXIS,
    cluster_mesh,
    cluster_sharding,
    initialize_distributed,
    shard_batch_arrays,
)

__all__ = [
    "CLUSTER_AXIS",
    "cluster_mesh",
    "cluster_sharding",
    "initialize_distributed",
    "shard_batch_arrays",
]
