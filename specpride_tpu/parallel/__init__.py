"""Device-mesh parallelism: shard the cluster axis across TPU chips/hosts.

The reference is single-threaded Python (survey §2 "Parallelism: none");
the workload is embarrassingly parallel across clusters, so the scale-out
design is: one 1-D ``jax.sharding.Mesh`` over all devices, every batched
kernel input sharded along its leading (cluster) axis, XLA SPMD-partitions
the vmapped programs with zero cross-device communication in the hot loop,
and the only collectives are the output all-gather and a final metrics
all-reduce (survey §2 / BASELINE.json config 5).

Four submodules sit beside the mesh: :mod:`.store` (the pluggable
coordinator state backend — shared directory or conditional-put object
store, plus the in-tree CAS test server), :mod:`.coordinator` (the
elastic work queue — leases, heartbeats, exactly-once range commits,
live work-stealing), :mod:`.elastic` (journal audits, the stats rank
view, manifest-verified merging) and :mod:`.fleet` (the warm-spare
autoscaling supervisor behind ``specpride fleet``).  All four are
jax-free, so the mesh exports below resolve LAZILY — ``specpride
stats`` / ``merge-parts`` / ``fleet`` on a login node must not pay (or
require) a jax import.
"""

_MESH_EXPORTS = (
    "CLUSTER_AXIS",
    "cluster_mesh",
    "cluster_sharding",
    "initialize_distributed",
    "shard_batch_arrays",
)

__all__ = list(_MESH_EXPORTS)


def __getattr__(name):
    if name in _MESH_EXPORTS:
        from specpride_tpu.parallel import mesh

        return getattr(mesh, name)
    raise AttributeError(name)
