"""Elastic multi-host helpers: journal audits, the rank liveness view,
and manifest-verified merging.

The runtime lives in :mod:`specpride_tpu.parallel.coordinator` (leases,
heartbeats, commits) and the orchestration in ``cli._run_elastic``; this
module holds the jax-free consumers shared by ``specpride stats``,
``specpride merge-parts`` and the tests:

* :func:`audit_elastic` — every journaled ``lease_expire`` must pair
  with a ``chunk_reassign`` for the same range: an expiry nobody
  reassigned is lost work, exactly what the chaos CI pass exists to
  catch.
* :func:`summarize_ranks` — the per-rank liveness/throughput rollup
  (ranks seen, last-heartbeat age, chunks committed, ranges claimed,
  reassignments in/out) ``specpride stats`` renders from the merged
  ``.part<rank>`` journals.
* :func:`verify_part_manifest` / :func:`merge_qc_reports` — the
  ``merge-parts`` hardening: sha256-verify each shard against its
  schema-2 manifest before concatenating, and rebuild the merged QC
  report byte-identically to a single-host serial run's.
"""

from __future__ import annotations

import json
import os
import statistics


def audit_elastic(events: list[dict]) -> list[dict]:
    """Unpaired work-movement events.  Two pairings are audited the
    same way:

    * every ``lease_expire`` must pair with a ``chunk_reassign`` for
      the same range (a dead rank's work actually moved);
    * every ``lease_split`` must pair with a ``chunk_reassign`` for its
      ``new_range`` (a split-off tail was actually claimed — a ratified
      split nobody picked up is lost work exactly like an unreassigned
      expiry).

    Feed MERGED events from every rank's journal — an expiry and its
    reassignment live in the observer's journal, but a split lives in
    the DONOR's journal while the reassignment lives in the claimer's,
    so a multi-file audit must never depend on which file an event came
    from."""
    reassigned: dict[int, int] = {}
    for e in events:
        if e.get("event") == "chunk_reassign":
            k = e.get("range")
            if isinstance(k, int):
                reassigned[k] = reassigned.get(k, 0) + 1
    unmatched = []
    for e in events:
        ev = e.get("event")
        if ev == "lease_expire":
            k = e.get("range")
        elif ev == "lease_split":
            k = e.get("new_range")
        else:
            continue
        if isinstance(k, int) and reassigned.get(k, 0) > 0:
            reassigned[k] -= 1
        else:
            unmatched.append(e)
    return unmatched


def summarize_ranks(events_per_file: list[list[dict]]) -> dict | None:
    """The multi-host rank view: one row per rank seen across the
    journals, plus the expiry/reassignment pairing audit.  Returns None
    when no elastic events exist (non-elastic journals render as
    before)."""
    ranks: dict[int, dict] = {}

    def row(r) -> dict:
        return ranks.setdefault(int(r), {
            "heartbeats": 0, "last_heartbeat_ts": None,
            "last_holding": [], "ttl": None,
            "ranges_claimed": 0, "takeovers": 0, "chunks_committed": 0,
            "leases_expired": 0, "reassigned_away": 0,
            "lease_splits": 0, "steals": 0,
        })

    saw_elastic = False
    max_ts = None
    for events in events_per_file:
        # chunk_done events carry no rank: attribute them to the rank
        # whose elastic events share the file (one journal per rank)
        file_rank = None
        chunk_done = 0
        for e in events:
            ts = e.get("ts")
            if isinstance(ts, (int, float)):
                max_ts = ts if max_ts is None else max(max_ts, ts)
            ev = e.get("event")
            if ev == "heartbeat":
                saw_elastic = True
                r = row(e.get("rank", -1))
                r["heartbeats"] += 1
                if isinstance(ts, (int, float)) and (
                    r["last_heartbeat_ts"] is None
                    or ts >= r["last_heartbeat_ts"]
                ):
                    r["last_heartbeat_ts"] = ts
                    holding = e.get("holding")
                    r["last_holding"] = (
                        list(holding) if isinstance(holding, list) else []
                    )
                if isinstance(e.get("ttl"), (int, float)):
                    r["ttl"] = e["ttl"]
                file_rank = e.get("rank", file_rank)
            elif ev == "lease_claim":
                saw_elastic = True
                r = row(e.get("rank", -1))
                r["ranges_claimed"] += 1
                if e.get("takeover"):
                    r["takeovers"] += 1
                file_rank = e.get("rank", file_rank)
            elif ev == "lease_expire":
                saw_elastic = True
                row(e.get("rank", -1))["leases_expired"] += 1
            elif ev == "lease_split":
                saw_elastic = True
                row(e.get("rank", -1))["lease_splits"] += 1
            elif ev == "chunk_reassign":
                saw_elastic = True
                row(e.get("from_rank", -1))["reassigned_away"] += 1
                if e.get("via") == "lease_split":
                    row(e.get("to_rank", -1))["steals"] += 1
            elif ev == "chunk_done":
                chunk_done += 1
        if file_rank is not None and chunk_done:
            row(file_rank)["chunks_committed"] += chunk_done
    if not saw_elastic:
        return None
    for r in ranks.values():
        last = r.pop("last_heartbeat_ts")
        holding = r.pop("last_holding")
        ttl = r.pop("ttl")
        r["last_heartbeat_age_s"] = (
            round(max_ts - last, 3)
            if last is not None and max_ts is not None else None
        )
        # stale-but-alive: the rank's heartbeat went silent past its
        # TTL while it still HELD leases, yet nobody expired it — the
        # signature of a slow (throttled, swapping, noisy-neighbour)
        # rank a live fleet should be stealing from, rendered as a
        # `slow:` marker by `specpride stats`
        r["slow"] = bool(
            holding
            and isinstance(ttl, (int, float))
            and r["last_heartbeat_age_s"] is not None
            and r["last_heartbeat_age_s"] > ttl
            and r["leases_expired"] == 0
        )
    unpaired = audit_elastic(
        [e for events in events_per_file for e in events]
    )
    return {
        "ranks": {str(k): ranks[k] for k in sorted(ranks)},
        "reassignments": sum(
            r["reassigned_away"] for r in ranks.values()
        ),
        "lease_splits": sum(
            r["lease_splits"] for r in ranks.values()
        ),
        "unpaired_lease_expiries": len(unpaired),
    }


# -- manifest-verified merging ------------------------------------------


def elastic_range_table(spec: str) -> tuple[list[dict] | None, str | None]:
    """The EFFECTIVE range set of an elastic run: the base plan plus
    every overlay range the work-stealing handshake registered, with
    ratified cuts applied to their parents — sorted by cluster START,
    which is the concatenation order that reproduces single-host serial
    bytes (overlay ids are allocated past the base plan, so id order is
    NOT cluster order once a split happened).

    Returns ``(table, problem)``: ``table`` is a list of
    ``{"range_id", "start", "stop"}`` rows, or None with a problem
    string when the plan is unreadable or the effective ranges do not
    tile ``[0, n_clusters)`` exactly (overlapping or gapped splits —
    states the handshake cannot legally produce, so seeing one means
    the store was tampered with or torn)."""
    from specpride_tpu.parallel.coordinator import plan_ranges
    from specpride_tpu.parallel.store import store_from_spec

    store = store_from_spec(spec)
    got = store.get("plan.json")
    if got is None:
        return None, "no readable plan.json"
    plan = got[0]
    n = plan.get("n_clusters")
    size = plan.get("range_size")
    if not isinstance(n, int) or not isinstance(size, int):
        return None, "malformed plan.json"
    rows = {
        r.range_id: {"range_id": r.range_id, "start": r.start,
                     "stop": r.stop}
        for r in plan_ranges(n, size)
    }
    # splits are discovered from CUT records only — the single atomic
    # publication of the handshake.  Overlay records are id-allocation
    # markers; one without a referencing cut is debris from a donor
    # that died mid-handshake and must NOT appear in the table (its
    # parent was never narrowed).
    for key in store.list_keys("split/"):
        if ".cut." not in key:
            continue
        rec_got = store.get(key)
        if rec_got is None:
            return None, f"unreadable cut record {key}"
        rec = rec_got[0]
        cut = rec.get("cut")
        try:
            parent = int(
                key.rsplit("/", 1)[1].split(".", 1)[0].replace("range_", "")
            )
        except ValueError:
            continue
        if not isinstance(cut, int):
            return None, f"malformed cut record {key}"
        rid = rec.get("new_range")
        if isinstance(rid, int):
            stop = rec.get("stop")
            if not isinstance(stop, int):
                return None, f"malformed cut record {key}"
            rows[rid] = {"range_id": rid, "start": cut, "stop": stop}
        row = rows.get(parent)
        if row is not None and cut < row["stop"]:
            row["stop"] = cut
    table = sorted(rows.values(), key=lambda r: (r["start"], r["range_id"]))
    pos = 0
    for row in table:
        if row["start"] != pos or row["stop"] < row["start"]:
            return None, (
                f"effective ranges do not tile the input: range "
                f"{row['range_id']} spans [{row['start']}, {row['stop']}) "
                f"but cluster {pos} is next"
            )
        pos = row["stop"]
    if pos != n:
        return None, (
            f"effective ranges cover {pos} of {n} clusters"
        )
    return table, None


def read_done_marker(spec: str, range_id: int) -> dict | None:
    """Range ``range_id``'s commit marker (None = never committed)."""
    from specpride_tpu.parallel.store import store_from_spec

    got = store_from_spec(spec).get(f"done/range_{range_id:05d}.json")
    return got[0] if got is not None else None


def sha256_file(path: str, upto: int | None = None) -> str:
    """sha256 of the first ``upto`` bytes (whole file when None) — the
    same chunked prefix hash the commit protocol maintains, via the ONE
    implementation in ``robustness.integrity`` (jax-free) so the two
    can never diverge."""
    from specpride_tpu.robustness.integrity import OutputIntegrity

    if upto is None:
        upto = os.path.getsize(path)
    return OutputIntegrity().seed_file(path, upto)


def verify_part_manifest(part: str, manifest: dict) -> str | None:
    """Check one output shard against its schema-2 manifest.  Returns a
    problem string (None = verified): size mismatch, sha256 mismatch, or
    a manifest too old to carry a hash."""
    want_bytes = manifest.get("output_bytes")
    if not isinstance(want_bytes, int):
        return "manifest records no output_bytes"
    try:
        size = os.path.getsize(part)
    except OSError as e:
        return f"unreadable part ({e})"
    if size != want_bytes:
        return (
            f"part is {size} bytes but its manifest committed "
            f"{want_bytes}"
        )
    want_sha = manifest.get("sha256")
    if not want_sha:
        return "manifest has no sha256 (pre-schema-2)"
    got = sha256_file(part, want_bytes)
    if got != want_sha:
        return (
            f"sha256 mismatch: manifest {want_sha[:12]}… vs part "
            f"{got[:12]}…"
        )
    return None


def merge_qc_reports(shards: list[str], out_path: str) -> int:
    """Merge per-shard QC reports (rank order) into one report that is
    byte-identical to the report a single-host serial run writes:
    same key order, same ``statistics`` aggregation over the same row
    sequence, same ``indent=1`` serialization.  Returns the merged
    cluster-row count."""
    rows: list[dict] = []
    n_input = 0
    method_failed: list[str] = []
    qc_failed: list[str] = []
    for path in shards:
        with open(path, encoding="utf-8") as fh:
            shard = json.load(fh)
        summary = shard.get("summary", {})
        rows.extend(shard.get("clusters", []))
        n_input += int(summary.get("n_input_clusters", 0))
        method_failed.extend(summary.get("method_failed_cluster_ids", []))
        qc_failed.extend(summary.get("qc_failed_cluster_ids", []))
    cosines = [row["avg_cosine"] for row in rows]
    method_failed = sorted(set(method_failed))
    qc_failed = sorted(set(qc_failed))
    report = {
        "summary": {
            "n_clusters": len(rows),
            "mean_cosine": statistics.fmean(cosines) if cosines else None,
            "median_cosine": (
                statistics.median(cosines) if cosines else None
            ),
            "n_input_clusters": n_input,
            "n_method_failed": len(method_failed),
            "n_qc_failed": len(qc_failed),
            **(
                {"method_failed_cluster_ids": method_failed}
                if method_failed else {}
            ),
            **({"qc_failed_cluster_ids": qc_failed} if qc_failed else {}),
        },
        "clusters": rows,
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    return len(rows)
