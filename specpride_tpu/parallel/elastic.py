"""Elastic multi-host helpers: journal audits, the rank liveness view,
and manifest-verified merging.

The runtime lives in :mod:`specpride_tpu.parallel.coordinator` (leases,
heartbeats, commits) and the orchestration in ``cli._run_elastic``; this
module holds the jax-free consumers shared by ``specpride stats``,
``specpride merge-parts`` and the tests:

* :func:`audit_elastic` — every journaled ``lease_expire`` must pair
  with a ``chunk_reassign`` for the same range: an expiry nobody
  reassigned is lost work, exactly what the chaos CI pass exists to
  catch.
* :func:`summarize_ranks` — the per-rank liveness/throughput rollup
  (ranks seen, last-heartbeat age, chunks committed, ranges claimed,
  reassignments in/out) ``specpride stats`` renders from the merged
  ``.part<rank>`` journals.
* :func:`verify_part_manifest` / :func:`merge_qc_reports` — the
  ``merge-parts`` hardening: sha256-verify each shard against its
  schema-2 manifest before concatenating, and rebuild the merged QC
  report byte-identically to a single-host serial run's.
"""

from __future__ import annotations

import json
import os
import statistics


def audit_elastic(events: list[dict]) -> list[dict]:
    """Unpaired ``lease_expire`` events: each must be followed by a
    ``chunk_reassign`` for the same range (the stealing rank emits the
    pair back to back, so pairing is per-range and order-aware).
    Feed MERGED events from every rank's journal — the expiry and the
    reassignment always live in the observer's journal, but a multi-file
    audit must not depend on which file they came from."""
    reassigned: dict[int, int] = {}
    for e in events:
        if e.get("event") == "chunk_reassign":
            k = e.get("range")
            if isinstance(k, int):
                reassigned[k] = reassigned.get(k, 0) + 1
    unmatched = []
    for e in events:
        if e.get("event") != "lease_expire":
            continue
        k = e.get("range")
        if isinstance(k, int) and reassigned.get(k, 0) > 0:
            reassigned[k] -= 1
        else:
            unmatched.append(e)
    return unmatched


def summarize_ranks(events_per_file: list[list[dict]]) -> dict | None:
    """The multi-host rank view: one row per rank seen across the
    journals, plus the expiry/reassignment pairing audit.  Returns None
    when no elastic events exist (non-elastic journals render as
    before)."""
    ranks: dict[int, dict] = {}

    def row(r) -> dict:
        return ranks.setdefault(int(r), {
            "heartbeats": 0, "last_heartbeat_ts": None,
            "ranges_claimed": 0, "takeovers": 0, "chunks_committed": 0,
            "leases_expired": 0, "reassigned_away": 0,
        })

    saw_elastic = False
    max_ts = None
    for events in events_per_file:
        # chunk_done events carry no rank: attribute them to the rank
        # whose elastic events share the file (one journal per rank)
        file_rank = None
        chunk_done = 0
        for e in events:
            ts = e.get("ts")
            if isinstance(ts, (int, float)):
                max_ts = ts if max_ts is None else max(max_ts, ts)
            ev = e.get("event")
            if ev == "heartbeat":
                saw_elastic = True
                r = row(e.get("rank", -1))
                r["heartbeats"] += 1
                if isinstance(ts, (int, float)):
                    r["last_heartbeat_ts"] = (
                        ts if r["last_heartbeat_ts"] is None
                        else max(r["last_heartbeat_ts"], ts)
                    )
                file_rank = e.get("rank", file_rank)
            elif ev == "lease_claim":
                saw_elastic = True
                r = row(e.get("rank", -1))
                r["ranges_claimed"] += 1
                if e.get("takeover"):
                    r["takeovers"] += 1
                file_rank = e.get("rank", file_rank)
            elif ev == "lease_expire":
                saw_elastic = True
                row(e.get("rank", -1))["leases_expired"] += 1
            elif ev == "chunk_reassign":
                saw_elastic = True
                row(e.get("from_rank", -1))["reassigned_away"] += 1
            elif ev == "chunk_done":
                chunk_done += 1
        if file_rank is not None and chunk_done:
            row(file_rank)["chunks_committed"] += chunk_done
    if not saw_elastic:
        return None
    for r in ranks.values():
        last = r.pop("last_heartbeat_ts")
        r["last_heartbeat_age_s"] = (
            round(max_ts - last, 3)
            if last is not None and max_ts is not None else None
        )
    unpaired = audit_elastic(
        [e for events in events_per_file for e in events]
    )
    return {
        "ranks": {str(k): ranks[k] for k in sorted(ranks)},
        "reassignments": sum(
            r["reassigned_away"] for r in ranks.values()
        ),
        "unpaired_lease_expiries": len(unpaired),
    }


# -- manifest-verified merging ------------------------------------------


def sha256_file(path: str, upto: int | None = None) -> str:
    """sha256 of the first ``upto`` bytes (whole file when None) — the
    same chunked prefix hash the commit protocol maintains, via the ONE
    implementation in ``robustness.integrity`` (jax-free) so the two
    can never diverge."""
    from specpride_tpu.robustness.integrity import OutputIntegrity

    if upto is None:
        upto = os.path.getsize(path)
    return OutputIntegrity().seed_file(path, upto)


def verify_part_manifest(part: str, manifest: dict) -> str | None:
    """Check one output shard against its schema-2 manifest.  Returns a
    problem string (None = verified): size mismatch, sha256 mismatch, or
    a manifest too old to carry a hash."""
    want_bytes = manifest.get("output_bytes")
    if not isinstance(want_bytes, int):
        return "manifest records no output_bytes"
    try:
        size = os.path.getsize(part)
    except OSError as e:
        return f"unreadable part ({e})"
    if size != want_bytes:
        return (
            f"part is {size} bytes but its manifest committed "
            f"{want_bytes}"
        )
    want_sha = manifest.get("sha256")
    if not want_sha:
        return "manifest has no sha256 (pre-schema-2)"
    got = sha256_file(part, want_bytes)
    if got != want_sha:
        return (
            f"sha256 mismatch: manifest {want_sha[:12]}… vs part "
            f"{got[:12]}…"
        )
    return None


def merge_qc_reports(shards: list[str], out_path: str) -> int:
    """Merge per-shard QC reports (rank order) into one report that is
    byte-identical to the report a single-host serial run writes:
    same key order, same ``statistics`` aggregation over the same row
    sequence, same ``indent=1`` serialization.  Returns the merged
    cluster-row count."""
    rows: list[dict] = []
    n_input = 0
    method_failed: list[str] = []
    qc_failed: list[str] = []
    for path in shards:
        with open(path, encoding="utf-8") as fh:
            shard = json.load(fh)
        summary = shard.get("summary", {})
        rows.extend(shard.get("clusters", []))
        n_input += int(summary.get("n_input_clusters", 0))
        method_failed.extend(summary.get("method_failed_cluster_ids", []))
        qc_failed.extend(summary.get("qc_failed_cluster_ids", []))
    cosines = [row["avg_cosine"] for row in rows]
    method_failed = sorted(set(method_failed))
    qc_failed = sorted(set(qc_failed))
    report = {
        "summary": {
            "n_clusters": len(rows),
            "mean_cosine": statistics.fmean(cosines) if cosines else None,
            "median_cosine": (
                statistics.median(cosines) if cosines else None
            ),
            "n_input_clusters": n_input,
            "n_method_failed": len(method_failed),
            "n_qc_failed": len(qc_failed),
            **(
                {"method_failed_cluster_ids": method_failed}
                if method_failed else {}
            ),
            **({"qc_failed_cluster_ids": qc_failed} if qc_failed else {}),
        },
        "clusters": rows,
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    return len(rows)
