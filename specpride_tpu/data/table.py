"""Columnar spectra dataset: the framework's hot-path data layout.

The reference moves data as Python objects (pyteomics dicts / lists of
spectra), which caps every pipeline stage at Python-loop speed.  Here the
canonical in-memory form is ONE flat columnar table — all peaks of all
spectra concatenated, with offset arrays — so that every host-side stage
(cluster assembly, bucketing, quantization, packing into device batches) is
a vectorized numpy pass over flat arrays, and the C++ MGF parser
(``io.native``) can materialise it directly from its column output without
ever constructing per-spectrum Python objects.

``Spectrum``/``Cluster`` (``data.peaks``) remain the user-facing staging
types; ``SpectraTable.from_clusters`` / ``to_clusters`` convert at the
boundary.  Device batches are built from tables by the vectorized packers in
``data.packed``.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from specpride_tpu.data.peaks import Cluster, Spectrum, parse_title


@dataclasses.dataclass
class SpectraTable:
    """S spectra / P peaks in flat columns, with per-spectrum cluster codes.

    Spectra keep file order.  ``cluster_code[s]`` indexes
    ``cluster_names``; codes are assigned in first-seen order (the
    reference's cluster iteration order, ref src/binning.py:159-165)."""

    mz: np.ndarray  # (P,) f64 — all peaks, spectrum-major
    intensity: np.ndarray  # (P,) f64
    peak_offsets: np.ndarray  # (S+1,) i64
    precursor_mz: np.ndarray  # (S,) f64
    precursor_charge: np.ndarray  # (S,) i32
    rt: np.ndarray  # (S,) f64
    titles: list[str]  # (S,)
    cluster_code: np.ndarray  # (S,) i64 — index into cluster_names
    cluster_names: list[str]

    @property
    def n_spectra(self) -> int:
        return len(self.titles)

    @property
    def n_peaks(self) -> int:
        return int(self.mz.size)

    @property
    def n_clusters(self) -> int:
        return len(self.cluster_names)

    @property
    def peak_counts(self) -> np.ndarray:
        """(S,) peaks per spectrum."""
        return np.diff(self.peak_offsets)

    def spectrum(self, s: int) -> Spectrum:
        lo, hi = int(self.peak_offsets[s]), int(self.peak_offsets[s + 1])
        return Spectrum(
            mz=self.mz[lo:hi],
            intensity=self.intensity[lo:hi],
            precursor_mz=float(self.precursor_mz[s]),
            precursor_charge=int(self.precursor_charge[s]),
            rt=float(self.rt[s]),
            title=self.titles[s],
        )

    def to_clusters(self) -> list[Cluster]:
        """Materialise Cluster objects (first-seen cluster order, in-file
        member order) — the object-API boundary, not a hot path."""
        members: list[list[Spectrum]] = [[] for _ in self.cluster_names]
        for s in range(self.n_spectra):
            members[int(self.cluster_code[s])].append(self.spectrum(s))
        return [
            Cluster(name, mem) for name, mem in zip(self.cluster_names, members)
        ]

    @classmethod
    def from_spectra(cls, spectra: Sequence[Spectrum]) -> "SpectraTable":
        """Build from Spectrum objects, parsing cluster ids from titles."""
        s_count = len(spectra)
        counts = np.fromiter(
            (s.n_peaks for s in spectra), dtype=np.int64, count=s_count
        )
        offsets = np.zeros(s_count + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        mz = (
            np.concatenate([s.mz for s in spectra])
            if s_count
            else np.zeros(0, np.float64)
        )
        inten = (
            np.concatenate([s.intensity for s in spectra])
            if s_count
            else np.zeros(0, np.float64)
        )
        titles = [s.title for s in spectra]
        codes = np.zeros(s_count, dtype=np.int64)
        names: list[str] = []
        index: dict[str, int] = {}
        for i, t in enumerate(titles):
            cid = parse_title(t)[0]
            code = index.get(cid)
            if code is None:
                code = index[cid] = len(names)
                names.append(cid)
            codes[i] = code
        return cls(
            mz=np.ascontiguousarray(mz, dtype=np.float64),
            intensity=np.ascontiguousarray(inten, dtype=np.float64),
            peak_offsets=offsets,
            precursor_mz=np.array(
                [s.precursor_mz for s in spectra], dtype=np.float64
            ),
            precursor_charge=np.array(
                [s.precursor_charge for s in spectra], dtype=np.int32
            ),
            rt=np.array([s.rt for s in spectra], dtype=np.float64),
            titles=titles,
            cluster_code=codes,
            cluster_names=names,
        )

    @classmethod
    def from_clusters(cls, clusters: Sequence[Cluster]) -> "SpectraTable":
        """Build from Cluster objects.  Cluster codes follow the given list
        order; members stay contiguous."""
        spectra: list[Spectrum] = []
        codes: list[int] = []
        names: list[str] = []
        for ci, c in enumerate(clusters):
            names.append(c.cluster_id)
            for s in c.members:
                spectra.append(s)
                codes.append(ci)
        table = cls.from_spectra(spectra)
        # override title-derived grouping with the explicit cluster structure
        # (titles may be absent or disagree when callers build clusters
        # programmatically)
        table.cluster_code = np.asarray(codes, dtype=np.int64)
        table.cluster_names = names
        return table

    @classmethod
    def from_columns(
        cls,
        mz: np.ndarray,
        intensity: np.ndarray,
        peak_offsets: np.ndarray,
        precursor_mz: np.ndarray,
        precursor_charge: np.ndarray,
        rt: np.ndarray,
        titles: list[str],
    ) -> "SpectraTable":
        """Build from raw parser columns (the ``io.native`` fast path),
        deriving cluster codes from titles in first-seen order."""
        codes = np.zeros(len(titles), dtype=np.int64)
        names: list[str] = []
        index: dict[str, int] = {}
        for i, t in enumerate(titles):
            cid = parse_title(t)[0]
            code = index.get(cid)
            if code is None:
                code = index[cid] = len(names)
                names.append(cid)
            codes[i] = code
        return cls(
            mz=np.ascontiguousarray(mz, dtype=np.float64),
            intensity=np.ascontiguousarray(intensity, dtype=np.float64),
            peak_offsets=np.ascontiguousarray(peak_offsets, dtype=np.int64),
            precursor_mz=np.ascontiguousarray(precursor_mz, dtype=np.float64),
            precursor_charge=np.ascontiguousarray(
                precursor_charge, dtype=np.int32
            ),
            rt=np.ascontiguousarray(rt, dtype=np.float64),
            titles=titles,
            cluster_code=codes,
            cluster_names=names,
        )

    # -- derived, cached cluster-level structure -------------------------

    def cluster_order(self) -> "ClusterIndex":
        """Spectrum ordering grouped by cluster + per-cluster extents (one
        stable argsort; cached)."""
        cached = getattr(self, "_cluster_index", None)
        if cached is not None:
            return cached
        idx = ClusterIndex.build(self)
        object.__setattr__(self, "_cluster_index", idx)
        return idx


@dataclasses.dataclass
class ClusterIndex:
    """Vectorized cluster structure over a SpectraTable.

    ``order`` lists spectrum indices grouped by cluster code (stable — file
    order within a cluster, matching the reference's member order);
    derived arrays give each spectrum's member index and each cluster's
    member/peak extent without any per-cluster Python."""

    order: np.ndarray  # (S,) spectrum indices, cluster-grouped
    spec_first: np.ndarray  # (S,) position-in-order of own cluster's first
    member_index: np.ndarray  # (S,) member position within cluster, in order
    n_members: np.ndarray  # (C,) members per cluster
    total_peaks: np.ndarray  # (C,) peaks per cluster
    cluster_start: np.ndarray  # (C,) position-in-order of first member
    max_members: int

    def first_spectrum(self) -> np.ndarray:
        """(C,) spectrum id of each cluster's first (file-order) member;
        0 for empty clusters."""
        safe = np.minimum(self.cluster_start, max(len(self.order) - 1, 0))
        return self.order[safe] if len(self.order) else safe

    def member_spectrum(self, codes: np.ndarray, member: np.ndarray) -> np.ndarray:
        """(len(codes),) spectrum id of member ``member[i]`` of cluster
        ``codes[i]``."""
        return self.order[self.cluster_start[codes] + member]

    @classmethod
    def build(cls, table: SpectraTable) -> "ClusterIndex":
        s_count = table.n_spectra
        c_count = table.n_clusters
        order = np.argsort(table.cluster_code, kind="stable")
        sorted_code = table.cluster_code[order]
        n_members = np.bincount(
            table.cluster_code, minlength=c_count
        ).astype(np.int64)
        counts = table.peak_counts
        total_peaks = np.bincount(
            table.cluster_code, weights=counts, minlength=c_count
        ).astype(np.int64)
        # position-in-order of each cluster's first spectrum
        cluster_start = np.zeros(c_count, dtype=np.int64)
        if s_count:
            first_mask = np.concatenate(
                [[True], sorted_code[1:] != sorted_code[:-1]]
            )
            cluster_start[sorted_code[first_mask]] = np.flatnonzero(first_mask)
        spec_first = cluster_start[sorted_code]
        member_index = np.arange(s_count, dtype=np.int64) - spec_first
        return cls(
            order=order,
            spec_first=spec_first,
            member_index=member_index,
            n_members=n_members,
            total_peaks=total_peaks,
            cluster_start=cluster_start,
            max_members=int(n_members.max(initial=0)),
        )
