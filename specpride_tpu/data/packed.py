"""Packed ragged batches: peaks stored contiguously per cluster.

The padded ``(cluster, member, peak)`` layout (``data.ragged``) wastes most
of its bytes on mask padding — with realistic clusters (e.g. 5×250 peaks in
a 32×512 bucket) >90% of host↔device traffic is padding.  The packed layout
stores each cluster's peaks contiguously along one axis with a parallel
``member_id`` channel:

    mz, intensity : (B, K) float32   — all member peaks, concatenated
    member_id     : (B, K) int32     — which member each peak belongs to;
                                        -1 marks padding slots
    (B, M) per-member metadata (precursor, rt, raw peak counts) kept dense.

K is the bucketed *total* peak count per cluster, so padding waste is
bounded by bucket granularity on one axis instead of two.  The consensus
kernels never needed the (member, peak) rectangle — binning flattens it
(ref src/binning.py:185-199), gap-averaging concatenates it (ref
src/average_spectrum_clustering.py:56-57), and the medoid occupancy scatter
indexes (member, bin) directly — so packing loses nothing and turns every
kernel into dense sort/segment work on K elements.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Iterable, Sequence

import numpy as np

from specpride_tpu.config import BatchConfig
from specpride_tpu.data.peaks import Cluster


@dataclasses.dataclass
class PackedBatch:
    """B clusters, each with up to K packed peaks and up to M members."""

    mz: np.ndarray  # (B, K) float32
    mz64: np.ndarray  # (B, K) float64 — HOST-ONLY exact m/z for quantization
    intensity: np.ndarray  # (B, K) float32
    member_id: np.ndarray  # (B, K) int32, -1 = padding
    n_peaks_total: np.ndarray  # (B,) int32 valid peaks per cluster
    n_members: np.ndarray  # (B,) int32
    member_mask: np.ndarray  # (B, M) bool
    precursor_mz: np.ndarray  # (B, M) float32
    precursor_charge: np.ndarray  # (B, M) int32
    rt: np.ndarray  # (B, M) float32
    n_peaks: np.ndarray  # (B, M) int32 raw per-member peak counts
    cluster_ids: list[str]
    source_indices: list[int]

    @property
    def n_clusters(self) -> int:
        return self.mz.shape[0]

    @property
    def k(self) -> int:
        return self.mz.shape[1]

    @property
    def m(self) -> int:
        return self.member_mask.shape[1]


def pack_clusters(
    clusters: Sequence[Cluster],
    k: int,
    m: int,
    source_indices: Sequence[int] | None = None,
) -> PackedBatch:
    """Pack a homogeneous group of clusters into one PackedBatch."""
    b = len(clusters)
    mz = np.zeros((b, k), dtype=np.float32)
    mz64 = np.zeros((b, k), dtype=np.float64)
    intensity = np.zeros((b, k), dtype=np.float32)
    member_id = np.full((b, k), -1, dtype=np.int32)
    n_peaks_total = np.zeros((b,), dtype=np.int32)
    n_members = np.zeros((b,), dtype=np.int32)
    member_mask = np.zeros((b, m), dtype=bool)
    precursor_mz = np.zeros((b, m), dtype=np.float32)
    precursor_charge = np.zeros((b, m), dtype=np.int32)
    rt = np.zeros((b, m), dtype=np.float32)
    n_peaks = np.zeros((b, m), dtype=np.int32)

    for ci, cluster in enumerate(clusters):
        if cluster.n_members > m:
            raise ValueError(
                f"cluster {cluster.cluster_id}: {cluster.n_members} members "
                f"> member bucket {m}"
            )
        if cluster.total_peaks > k:
            raise ValueError(
                f"cluster {cluster.cluster_id}: {cluster.total_peaks} peaks "
                f"> peak bucket {k}"
            )
        n_members[ci] = cluster.n_members
        pos = 0
        for mi, s in enumerate(cluster.members):
            np_ = s.n_peaks
            mz[ci, pos : pos + np_] = s.mz
            mz64[ci, pos : pos + np_] = s.mz
            intensity[ci, pos : pos + np_] = s.intensity
            member_id[ci, pos : pos + np_] = mi
            pos += np_
            member_mask[ci, mi] = True
            precursor_mz[ci, mi] = s.precursor_mz
            precursor_charge[ci, mi] = s.precursor_charge
            rt[ci, mi] = s.rt
            n_peaks[ci, mi] = np_
        n_peaks_total[ci] = pos

    return PackedBatch(
        mz=mz,
        mz64=mz64,
        intensity=intensity,
        member_id=member_id,
        n_peaks_total=n_peaks_total,
        n_members=n_members,
        member_mask=member_mask,
        precursor_mz=precursor_mz,
        precursor_charge=precursor_charge,
        rt=rt,
        n_peaks=n_peaks,
        cluster_ids=[c.cluster_id for c in clusters],
        source_indices=(
            list(source_indices) if source_indices is not None else list(range(b))
        ),
    )


@dataclasses.dataclass
class BinPackedBatch:
    """Packed batch specialised for binned-mean consensus: bin indices are
    quantized (float64) and duplicate-(member, bin) peaks dropped at pack
    time, so the device kernel needs no member channel at all.

    Dropping duplicates host-side is exact: a peak that is not the last
    occurrence of its (member, bin) pair contributes nothing under the
    reference's buffered ``+=`` semantics (ref src/binning.py:197-199), and
    after dedup every surviving peak adds exactly 1 to its bin's member
    count.  H2D traffic: 12 B/peak (mz, intensity, bin) and the peaks
    shrink by the duplicate fraction.
    """

    mz: np.ndarray  # (B, K) float32
    intensity: np.ndarray  # (B, K) float32
    bins: np.ndarray  # (B, K) int32, sentinel = n_bins for padding
    n_valid: np.ndarray  # (B,) int32
    n_members: np.ndarray  # (B,) int32
    cluster_ids: list[str]
    source_indices: list[int]


def _dedup_last_per_bin(bins: np.ndarray) -> np.ndarray:
    """Boolean keep-mask: last occurrence of each bin value within one
    member's peak array (array order = reference scatter order)."""
    if bins.size == 0:
        return np.zeros((0,), dtype=bool)
    if bins.size > 1 and np.all(np.diff(bins) >= 0):
        # sorted-m/z fast path: runs are contiguous
        return np.concatenate([bins[1:] != bins[:-1], [True]])
    # general: np.unique on the reversed array marks last occurrences
    _, first_of_reversed = np.unique(bins[::-1], return_index=True)
    keep = np.zeros(bins.shape, dtype=bool)
    keep[bins.size - 1 - first_of_reversed] = True
    return keep


def pack_bin_mean(
    clusters: Sequence[Cluster],
    bins_per_member: Sequence[Sequence[np.ndarray]],
    keep_per_member: Sequence[Sequence[np.ndarray]],
    k: int,
    source_indices: Sequence[int],
    sentinel: int,
) -> BinPackedBatch:
    """Assemble a BinPackedBatch from per-member quantized bins + keep masks
    (see ``pack_bucketize_bin_mean``)."""
    b = len(clusters)
    mz = np.zeros((b, k), dtype=np.float32)
    intensity = np.zeros((b, k), dtype=np.float32)
    bins = np.full((b, k), sentinel, dtype=np.int32)
    n_valid = np.zeros((b,), dtype=np.int32)
    n_members = np.zeros((b,), dtype=np.int32)
    for ci, cluster in enumerate(clusters):
        pos = 0
        for s, mb, kp in zip(
            cluster.members, bins_per_member[ci], keep_per_member[ci]
        ):
            kept = int(kp.sum())
            mz[ci, pos : pos + kept] = s.mz[kp]
            intensity[ci, pos : pos + kept] = s.intensity[kp]
            bins[ci, pos : pos + kept] = mb[kp]
            pos += kept
        n_valid[ci] = pos
        n_members[ci] = cluster.n_members
    return BinPackedBatch(
        mz=mz,
        intensity=intensity,
        bins=bins,
        n_valid=n_valid,
        n_members=n_members,
        cluster_ids=[c.cluster_id for c in clusters],
        source_indices=list(source_indices),
    )


@dataclasses.dataclass
class GapPackedBatch:
    """Packed batch specialised for gap-average consensus: member peaks are
    concatenated, sorted, and split into gap segments in FLOAT64 on the host
    at pack time (the f64-sensitive step — comparing m/z diffs against
    ``mz_accuracy``, ref src/average_spectrum_clustering.py:62-67 — cannot
    run in device f32 without silently regrouping peaks; see
    ``ops.gap_average`` module docstring).  The device receives only sorted
    f32 peaks + int32 segment ids and does the heavy segment reductions.

    ``n_groups`` is the exact per-cluster group count (known host-side), so
    device output buffers are sized exactly — no overflow/redispatch."""

    mz: np.ndarray  # (B, K) f32, sorted ascending (singletons: input order)
    intensity: np.ndarray  # (B, K) f32, in the same order
    seg: np.ndarray  # (B, K) i32 segment ids, non-decreasing; padding = 0
    n_valid: np.ndarray  # (B,) i32
    quorum: np.ndarray  # (B,) i32 f64-exact ceil(min_fraction * n_members)
    n_members: np.ndarray  # (B,) i32
    n_groups: np.ndarray  # (B,) i64 exact group count (output bound)
    cluster_ids: list[str]
    source_indices: list[int]


def pack_bucketize_gap(
    clusters: Iterable[Cluster],
    config,
    batch_config: BatchConfig = BatchConfig(),
) -> list[GapPackedBatch]:
    """Sort + f64 gap-segment each cluster (``ops.quantize.gap_segments`` —
    the same grouping code the numpy oracle runs), then bucket by total peak
    count for the gap-average kernel
    (``ops.gap_average.gap_average_compact``)."""
    from specpride_tpu.ops.quantize import gap_segments

    prepared = []  # (i, cluster, mz, inten, seg)
    for i, c in enumerate(clusters):
        if c.n_members == 0:
            continue
        prepared.append((i, c, *gap_segments(c.members, config)))

    buckets: dict[int, list] = {}
    for item in prepared:
        kkey = _bucket_for(max(item[2].size, 1), batch_config.total_peak_buckets)
        buckets.setdefault(kkey, []).append(item)

    batches: list[GapPackedBatch] = []
    for kkey, group in buckets.items():
        for start in range(0, len(group), batch_config.clusters_per_batch):
            chunk = group[start : start + batch_config.clusters_per_batch]
            b = len(chunk)
            mz = np.zeros((b, kkey), dtype=np.float32)
            inten = np.zeros((b, kkey), dtype=np.float32)
            seg = np.zeros((b, kkey), dtype=np.int32)
            n_valid = np.zeros((b,), dtype=np.int32)
            quorum = np.zeros((b,), dtype=np.int32)
            n_members = np.zeros((b,), dtype=np.int32)
            n_groups = np.zeros((b,), dtype=np.int64)
            for ci, (_, c, cmz, cint, cseg) in enumerate(chunk):
                n = cmz.size
                mz[ci, :n] = cmz
                inten[ci, :n] = cint
                seg[ci, :n] = cseg
                n_valid[ci] = n
                # integer quorum, exact in f64: for integer group sizes s,
                # s >= min_fraction*n  <=>  s >= ceil(min_fraction*n)
                quorum[ci] = int(np.ceil(config.min_fraction * c.n_members))
                n_members[ci] = c.n_members
                n_groups[ci] = int(cseg[-1]) + 1 if n else 0
            batches.append(
                GapPackedBatch(
                    mz=mz,
                    intensity=inten,
                    seg=seg,
                    n_valid=n_valid,
                    quorum=quorum,
                    n_members=n_members,
                    n_groups=n_groups,
                    cluster_ids=[c.cluster_id for _, c, _, _, _ in chunk],
                    source_indices=[i for i, _, _, _, _ in chunk],
                )
            )
    return batches


def pack_bucketize_bin_mean(
    clusters: Iterable[Cluster],
    min_mz: float,
    max_mz: float,
    bin_size: float,
    n_bins: int,
    config: BatchConfig = BatchConfig(),
) -> list[BinPackedBatch]:
    """Quantize (float64), dedup, and bucket clusters for the binned-mean
    kernel.  K buckets are chosen on the DEDUPED, range-filtered peak
    counts."""
    prepared = []  # (i, cluster, bins_per_member, keep_per_member, total)
    for i, c in enumerate(clusters):
        if c.n_members == 0:
            continue
        mbs, kps, total = [], [], 0
        for s in c.members:
            mz64 = s.mz.astype(np.float64, copy=False)
            in_range = (mz64 >= min_mz) & (mz64 < max_mz)
            b = ((mz64 - min_mz) / bin_size).astype(np.int64)
            b = np.where(in_range, np.clip(b, 0, n_bins - 1), -1)
            keep = _dedup_last_per_bin(b) & in_range
            mbs.append(b.astype(np.int32))
            kps.append(keep)
            total += int(keep.sum())
        prepared.append((i, c, mbs, kps, total))

    buckets: dict[int, list] = {}
    for item in prepared:
        kkey = _bucket_for(max(item[4], 1), config.total_peak_buckets)
        buckets.setdefault(kkey, []).append(item)

    batches: list[BinPackedBatch] = []
    for kkey, group in buckets.items():
        for start in range(0, len(group), config.clusters_per_batch):
            chunk = group[start : start + config.clusters_per_batch]
            batches.append(
                pack_bin_mean(
                    [c for _, c, _, _, _ in chunk],
                    [m for _, _, m, _, _ in chunk],
                    [k2 for _, _, _, k2, _ in chunk],
                    kkey,
                    [i for i, _, _, _, _ in chunk],
                    n_bins,
                )
            )
    return batches


def _bucket_for(value: int, buckets: Sequence[int]) -> int:
    i = bisect.bisect_left(buckets, value)
    if i < len(buckets):
        return buckets[i]
    return 1 << (max(value, 1) - 1).bit_length()  # grow past the last bucket


def pack_bucketize(
    clusters: Iterable[Cluster],
    config: BatchConfig = BatchConfig(),
    bucket_members: bool = False,
) -> list[PackedBatch]:
    """Group clusters into PackedBatches of homogeneous K bucket shape,
    recording original positions in ``source_indices``.

    With ``bucket_members=False`` (default) the member axis M is sized to
    the largest cluster in each batch — right for kernels that never ship
    the (B, M) metadata to the device (bin-mean, gap-average), since every
    distinct batch shape is one XLA compile and one set of transfers.
    ``bucket_members=True`` additionally buckets M (medoid occupancy needs
    a device (B, M, grid) tensor)."""
    buckets: dict[tuple[int, int], list[tuple[int, Cluster]]] = {}
    for i, c in enumerate(clusters):
        if c.n_members == 0:
            continue
        kkey = _bucket_for(max(c.total_peaks, 1), config.total_peak_buckets)
        mkey = _bucket_for(c.n_members, config.member_buckets) if bucket_members else 0
        buckets.setdefault((kkey, mkey), []).append((i, c))

    batches: list[PackedBatch] = []
    for (kkey, mkey), group in buckets.items():
        for start in range(0, len(group), config.clusters_per_batch):
            chunk = group[start : start + config.clusters_per_batch]
            if bucket_members:
                m = mkey
            else:
                # round to a power of two so the (B, M) metadata shape — and
                # the kernels' static m — stay stable across similar runs
                mx = max(c.n_members for _, c in chunk)
                m = 1 << (max(mx, 1) - 1).bit_length()
            batches.append(
                pack_clusters(
                    [c for _, c in chunk], kkey, m, [i for i, _ in chunk]
                )
            )
    return batches
