"""Packed ragged batches: peaks stored contiguously per cluster.

The padded ``(cluster, member, peak)`` layout wastes most of its bytes on
mask padding — with realistic clusters (e.g. 5×250 peaks in a 32×512
bucket) >90% of host↔device traffic is padding.  The packed layout stores
each cluster's peaks contiguously along one axis with a parallel
``member_id`` channel:

    mz, intensity : (B, K) float32   — all member peaks, concatenated
    member_id     : (B, K) int32     — which member each peak belongs to;
                                        -1 marks padding slots
    (B, M) per-member metadata (precursor, rt, raw peak counts) kept dense.

K is the bucketed *total* peak count per cluster, so padding waste is
bounded by bucket granularity on one axis instead of two.  The consensus
kernels never needed the (member, peak) rectangle — binning flattens it
(ref src/binning.py:185-199), gap-averaging concatenates it (ref
src/average_spectrum_clustering.py:56-57), and the medoid sort/segment
kernel indexes (bin, member) runs directly — so packing loses nothing and
turns every kernel into dense sort/segment work on K elements.

All packers are VECTORIZED over a columnar ``SpectraTable``
(``data.table``): bucketing, quantization, and the peak scatter into (B, K)
device buffers are flat numpy passes with no per-cluster Python loop — at
device throughputs the old per-cluster pack loop was the end-to-end
bottleneck.  ``list[Cluster]`` inputs are accepted everywhere and converted
at the boundary.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import threading
from collections import OrderedDict
from typing import Sequence

import numpy as np

from specpride_tpu.config import BatchConfig
from specpride_tpu.data.peaks import Cluster
from specpride_tpu.data.table import ClusterIndex, SpectraTable
from specpride_tpu.observability import tracing


def _as_table(clusters_or_table) -> SpectraTable:
    if isinstance(clusters_or_table, SpectraTable):
        return clusters_or_table
    return SpectraTable.from_clusters(clusters_or_table)


def _grouped_arange(counts: np.ndarray) -> np.ndarray:
    """[0..c0), [0..c1), ... concatenated (vectorized ragged arange)."""
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    ends = np.cumsum(counts)
    starts = ends - counts
    return np.arange(total, dtype=np.int64) - np.repeat(starts, counts)


def _bucket_for(value: int, buckets: Sequence[int]) -> int:
    i = bisect.bisect_left(buckets, value)
    if i < len(buckets):
        return buckets[i]
    return 1 << (max(value, 1) - 1).bit_length()  # grow past the last bucket


def _bucket_keys(values: np.ndarray, buckets: Sequence[int]) -> np.ndarray:
    """Vectorized ``_bucket_for``: bucket size per value."""
    values = np.maximum(values, 1)
    b = np.asarray(buckets, dtype=np.int64)
    idx = np.searchsorted(b, values, side="left")
    inside = idx < len(b)
    keys = np.where(inside, b[np.minimum(idx, len(b) - 1)], 0)
    if not inside.all():
        over = values[~inside]
        keys[~inside] = 1 << (
            np.ceil(np.log2(np.maximum(over, 2))).astype(np.int64)
        )
    return keys


@dataclasses.dataclass
class PackedBatch:
    """B clusters, each with up to K packed peaks and up to M members."""

    mz: np.ndarray  # (B, K) float32
    mz64: np.ndarray  # (B, K) float64 — HOST-ONLY exact m/z for quantization
    intensity: np.ndarray  # (B, K) float32
    member_id: np.ndarray  # (B, K) int32, -1 = padding
    n_peaks_total: np.ndarray  # (B,) int32 valid peaks per cluster
    n_members: np.ndarray  # (B,) int32
    member_mask: np.ndarray  # (B, M) bool
    precursor_mz: np.ndarray  # (B, M) float32
    precursor_charge: np.ndarray  # (B, M) int32
    rt: np.ndarray  # (B, M) float32
    n_peaks: np.ndarray  # (B, M) int32 raw per-member peak counts
    member_spec: np.ndarray  # (B, M) int64 table spectrum id, -1 = padding
    cluster_ids: list[str]
    source_indices: list[int]

    @property
    def n_clusters(self) -> int:
        return self.mz.shape[0]

    @property
    def k(self) -> int:
        return self.mz.shape[1]

    @property
    def m(self) -> int:
        return self.member_mask.shape[1]


@dataclasses.dataclass
class BinPackedBatch:
    """Packed batch specialised for binned-mean consensus: bin indices are
    quantized (float64) and duplicate-(member, bin) peaks dropped at pack
    time, so the device kernel needs no member channel at all.

    Dropping duplicates host-side is exact: a peak that is not the last
    occurrence of its (member, bin) pair contributes nothing under the
    reference's buffered ``+=`` semantics (ref src/binning.py:197-199), and
    after dedup every surviving peak adds exactly 1 to its bin's member
    count.  H2D traffic: 12 B/peak (mz, intensity, bin) and the peaks
    shrink by the duplicate fraction.

    Rows are PRE-SORTED by bin (padding sentinel last) at pack time — the
    device kernel (``ops.binning.bin_mean_deduped_compact``) requires
    non-decreasing bins per row and does no sorting of its own.
    """

    mz: np.ndarray  # (B, K) float32
    intensity: np.ndarray  # (B, K) float32
    bins: np.ndarray  # (B, K) int32, sentinel = n_bins for padding
    n_valid: np.ndarray  # (B,) int32
    n_members: np.ndarray  # (B,) int32
    cluster_ids: list[str]
    source_indices: list[int]


@dataclasses.dataclass
class GapPackedBatch:
    """Packed batch specialised for gap-average consensus: member peaks are
    concatenated, sorted, and split into gap segments in FLOAT64 on the host
    at pack time (the f64-sensitive step — comparing m/z diffs against
    ``mz_accuracy``, ref src/average_spectrum_clustering.py:62-67 — cannot
    run in device f32 without silently regrouping peaks; see
    ``ops.gap_average`` module docstring).  The device receives only sorted
    f32 peaks + int32 segment ids and does the heavy segment reductions.

    ``n_groups`` is the exact per-cluster group count (known host-side), so
    device output buffers are sized exactly — no overflow/redispatch."""

    mz: np.ndarray  # (B, K) f32, sorted ascending (singletons: input order)
    intensity: np.ndarray  # (B, K) f32, in the same order
    seg: np.ndarray  # (B, K) i32 segment ids, non-decreasing; padding = 0
    n_valid: np.ndarray  # (B,) i32
    quorum: np.ndarray  # (B,) i32 f64-exact ceil(min_fraction * n_members)
    n_members: np.ndarray  # (B,) i32
    n_groups: np.ndarray  # (B,) i64 exact group count (output bound)
    cluster_ids: list[str]
    source_indices: list[int]


def merge_cluster_sources(
    parts: "Sequence[Sequence[Cluster]]",
) -> tuple[list, list[tuple[int, int]]]:
    """Concatenate cluster lists from several SOURCES (the serving
    daemon's cross-job micro-batching: each source is one tenant job's
    parsed input) into ONE pack/dispatch input, with provenance spans
    for scattering per-cluster results back to each owning source.

    Every consensus/select method is per-cluster, so the merged list
    flows through the ordinary pack functions — which then build ONE
    bucket plan covering all sources instead of one under-filled plan
    per job — and per-cluster results are sliced back out by span.
    Returns ``(merged, spans)`` where ``spans[i] = (start, stop)`` is
    source ``i``'s half-open slice of ``merged`` (and of any
    cluster-aligned result list computed from it)."""
    merged: list = []
    spans: list[tuple[int, int]] = []
    for part in parts:
        start = len(merged)
        merged.extend(part)
        spans.append((start, len(merged)))
    return merged, spans


# ---------------------------------------------------------------------------
# Shared vectorized grouping machinery
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _BucketPlan:
    """One (K[, M]) bucket group of clusters, chunked by clusters_per_batch."""

    codes: np.ndarray  # cluster codes in this chunk, appearance order
    k: int
    m: int  # 0 when the member axis is unbucketed


# bucket-plan cache: the grouping loop below is Python-level (one pass per
# unique (K, M) bucket pair) and runs once per pack call.  Repeated chunks
# with the SAME cluster codes and bucket keys — steady-state bench reruns,
# a resume redoing its last chunk, the QC recompute pass, pipelined runs
# re-packing identical windows — skip re-planning entirely.  Keyed on a
# digest of (codes, kkeys, mkeys, clusters_per_batch); plans are treated as
# immutable by every consumer.  Thread-safe: the pipelined executor packs
# on a background thread while the main thread may pack QC batches.
_PLAN_CACHE: "OrderedDict[bytes, list[_BucketPlan]]" = OrderedDict()
_PLAN_CACHE_MAX = 128
_PLAN_CACHE_LOCK = threading.Lock()
_PLAN_CACHE_COUNTS = {"hits": 0, "misses": 0}


def plan_cache_info() -> dict:
    """{"hits", "misses", "size"} — observability + tests."""
    with _PLAN_CACHE_LOCK:
        return dict(_PLAN_CACHE_COUNTS, size=len(_PLAN_CACHE))


def plan_cache_delta(since: dict) -> dict:
    """Per-run view of the process-wide plan-cache counters: hits and
    misses since ``since`` (a ``plan_cache_info()`` snapshot), plus the
    absolute cache size.  Snapshot-and-diff, never reset: a long-lived
    multi-job process (the serving daemon) must attribute traffic to
    the job that caused it without zeroing another job's accounting
    mid-run."""
    now = plan_cache_info()
    return {
        "hits": now["hits"] - int(since.get("hits", 0)),
        "misses": now["misses"] - int(since.get("misses", 0)),
        "size": now["size"],
    }


def clear_plan_cache() -> None:
    with _PLAN_CACHE_LOCK:
        _PLAN_CACHE.clear()
        _PLAN_CACHE_COUNTS.update(hits=0, misses=0)


# per-JOB plan-cache attribution for multi-lane serving: the process-
# wide counters above stay the scrape/export truth, but a snapshot-diff
# of them cross-attributes once jobs pack on CONCURRENT worker lanes.
# A job installs a PlanCacheScope on every thread that packs for it
# (the dispatch lane plus its pack workers — cli wires the adoption at
# lane-thread start), and _plan_buckets bumps the calling thread's
# scope alongside the globals, under the same lock.
_SCOPE_TLS = threading.local()


class PlanCacheScope:
    """Per-job hit/miss counters; all mutation happens under
    ``_PLAN_CACHE_LOCK`` so a job's several pack threads share one
    scope safely."""

    __slots__ = ("hits", "misses")

    def __init__(self):
        self.hits = 0
        self.misses = 0

    def delta(self) -> dict:
        """The run_end ``plan_cache`` payload (same shape as
        :func:`plan_cache_delta`)."""
        with _PLAN_CACHE_LOCK:
            return {
                "hits": self.hits, "misses": self.misses,
                "size": len(_PLAN_CACHE),
            }


def set_plan_scope(scope: "PlanCacheScope | None") -> "PlanCacheScope | None":
    """Install ``scope`` as the CURRENT thread's plan-cache attribution
    target (None detaches); returns the previous scope so lane threads
    can restore on exit."""
    prev = getattr(_SCOPE_TLS, "scope", None)
    _SCOPE_TLS.scope = scope
    return prev


def current_plan_scope() -> "PlanCacheScope | None":
    return getattr(_SCOPE_TLS, "scope", None)


def _plan_buckets(
    idx: ClusterIndex,
    eligible: np.ndarray,  # (C,) bool
    totals: np.ndarray,  # (C,) value that picks the K bucket
    config: BatchConfig,
    bucket_members: bool,
) -> list[_BucketPlan]:
    codes = np.flatnonzero(eligible)
    if codes.size == 0:
        return []
    kkeys = _bucket_keys(totals[codes], config.total_peak_buckets)
    if bucket_members:
        mkeys = _bucket_keys(idx.n_members[codes], config.member_buckets)
    else:
        mkeys = np.zeros(codes.size, dtype=np.int64)
    h = hashlib.blake2b(digest_size=16)
    h.update(codes.tobytes())
    h.update(kkeys.tobytes())
    h.update(mkeys.tobytes())
    h.update(int(config.clusters_per_batch).to_bytes(8, "little"))
    key = h.digest()
    scope = current_plan_scope()
    with _PLAN_CACHE_LOCK:
        cached = _PLAN_CACHE.get(key)
        if cached is not None:
            _PLAN_CACHE_COUNTS["hits"] += 1
            if scope is not None:
                scope.hits += 1
            _PLAN_CACHE.move_to_end(key)
            return cached
        _PLAN_CACHE_COUNTS["misses"] += 1
        if scope is not None:
            scope.misses += 1
    plans: list[_BucketPlan] = []
    for kkey in np.unique(kkeys):
        for mkey in np.unique(mkeys[kkeys == kkey]):
            sel = codes[(kkeys == kkey) & (mkeys == mkey)]
            for start in range(0, sel.size, config.clusters_per_batch):
                chunk = sel[start : start + config.clusters_per_batch]
                plans.append(_BucketPlan(chunk, int(kkey), int(mkey)))
    with _PLAN_CACHE_LOCK:
        _PLAN_CACHE[key] = plans
        while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
            _PLAN_CACHE.popitem(last=False)
    return plans


def _peak_layout(table: SpectraTable, idx: ClusterIndex, plan: _BucketPlan):
    """Flat source/destination indices for scattering a plan's peaks into a
    (B, K) buffer in cluster-member-peak order.

    Returns (spec_ids, row_of_spec, member_idx, counts, src, dest) — all
    vectorized; ``src`` indexes ``table.mz``; ``dest`` indexes the flat
    (B*K,) buffer."""
    codes = plan.codes
    nm = idx.n_members[codes]
    # positions of each chosen cluster's spectra within idx.order
    first = np.zeros(len(idx.n_members), dtype=np.int64)
    np.cumsum(idx.n_members[:-1], out=first[1:])
    starts = first[codes]
    row_of_spec = np.repeat(np.arange(codes.size, dtype=np.int64), nm)
    member_idx = _grouped_arange(nm)
    spec_ids = idx.order[np.repeat(starts, nm) + member_idx]
    counts = table.peak_counts[spec_ids]
    # within-row start offset of each spectrum's peaks
    cs = np.concatenate([[0], np.cumsum(counts)])[:-1]
    row_spec_start = np.concatenate([[0], np.cumsum(nm)])[:-1]
    base = np.repeat(cs[row_spec_start], nm)
    within = cs - base
    src = np.repeat(table.peak_offsets[spec_ids], counts) + _grouped_arange(
        counts
    )
    dest = (
        np.repeat(row_of_spec, counts) * plan.k
        + np.repeat(within, counts)
        + _grouped_arange(counts)
    )
    return spec_ids, row_of_spec, member_idx, counts, src, dest


# ---------------------------------------------------------------------------
# Generic packed batches (medoid, cosine)
# ---------------------------------------------------------------------------


@tracing.traced("pack:bucketize")
def pack_bucketize(
    clusters_or_table,
    config: BatchConfig = BatchConfig(),
    bucket_members: bool = False,
) -> list[PackedBatch]:
    """Group clusters into PackedBatches of homogeneous K bucket shape,
    recording cluster codes in ``source_indices``.

    With ``bucket_members=False`` (default) the member axis M is sized to
    the largest cluster in each batch, rounded to a power of two — right for
    kernels where M shapes only small metadata.  ``bucket_members=True``
    buckets M explicitly (the medoid kernel's run×member occupancy shape)."""
    table = _as_table(clusters_or_table)
    idx = table.cluster_order()
    eligible = idx.n_members > 0
    plans = _plan_buckets(idx, eligible, idx.total_peaks, config, bucket_members)

    batches: list[PackedBatch] = []
    for plan in plans:
        codes = plan.codes
        b, k = codes.size, plan.k
        spec_ids, row_of_spec, member_idx, counts, src, dest = _peak_layout(
            table, idx, plan
        )
        if plan.m:
            m = plan.m
        else:
            mx = int(idx.n_members[codes].max(initial=1))
            m = 1 << (max(mx, 1) - 1).bit_length()

        mz64 = np.zeros(b * k, dtype=np.float64)
        mz64[dest] = table.mz[src]
        inten = np.zeros(b * k, dtype=np.float32)
        inten[dest] = table.intensity[src]
        member_id = np.full(b * k, -1, dtype=np.int32)
        member_id[dest] = np.repeat(member_idx, counts)

        member_mask = np.zeros((b, m), dtype=bool)
        member_mask[row_of_spec, member_idx] = True
        precursor_mz = np.zeros((b, m), dtype=np.float32)
        precursor_mz[row_of_spec, member_idx] = table.precursor_mz[spec_ids]
        precursor_charge = np.zeros((b, m), dtype=np.int32)
        precursor_charge[row_of_spec, member_idx] = table.precursor_charge[
            spec_ids
        ]
        rt = np.zeros((b, m), dtype=np.float32)
        rt[row_of_spec, member_idx] = table.rt[spec_ids]
        n_peaks = np.zeros((b, m), dtype=np.int32)
        n_peaks[row_of_spec, member_idx] = counts
        member_spec = np.full((b, m), -1, dtype=np.int64)
        member_spec[row_of_spec, member_idx] = spec_ids

        batches.append(
            PackedBatch(
                mz=mz64.astype(np.float32).reshape(b, k),
                mz64=mz64.reshape(b, k),
                intensity=inten.reshape(b, k),
                member_id=member_id.reshape(b, k),
                n_peaks_total=idx.total_peaks[codes].astype(np.int32),
                n_members=idx.n_members[codes].astype(np.int32),
                member_mask=member_mask,
                precursor_mz=precursor_mz,
                precursor_charge=precursor_charge,
                rt=rt,
                n_peaks=n_peaks,
                member_spec=member_spec,
                cluster_ids=[table.cluster_names[c] for c in codes],
                source_indices=[int(c) for c in codes],
            )
        )
    return batches


# ---------------------------------------------------------------------------
# Binned-mean packing (K1): f64 quantize + dedup, all vectorized
# ---------------------------------------------------------------------------


def _dedup_keep_mask(
    spec_of_peak: np.ndarray,  # (P,) i64 spectrum id per peak
    bins: np.ndarray,  # (P,) i64, -1 = out of range
    mz: np.ndarray,  # (P,) f64 — sortedness probe for the fast path
) -> np.ndarray:
    """Keep-mask: last occurrence of each (spectrum, bin) pair in array
    order, matching numpy's buffered fancy-index ``+=`` semantics (ref
    src/binning.py:197-199).

    Fast path: when every spectrum's m/z is non-decreasing (the MGF norm),
    duplicate bins are consecutive and out-of-range peaks sit only at the
    ends, so one vector compare suffices.  Fallback: a global
    (spectrum, bin, position) lexsort marks last occurrences for arbitrary
    orderings."""
    p = bins.size
    if p == 0:
        return np.zeros(0, dtype=bool)
    same_spec = spec_of_peak[1:] == spec_of_peak[:-1]
    if not (same_spec & (mz[1:] < mz[:-1])).any():
        consecutive_dup = same_spec & (bins[1:] == bins[:-1]) & (bins[1:] >= 0)
        keep = np.ones(p, dtype=bool)
        keep[:-1] &= ~consecutive_dup
        return keep
    # general: last occurrence per (spectrum, bin) via lexsort
    order = np.lexsort((np.arange(p), bins, spec_of_peak))
    sb = bins[order]
    ss = spec_of_peak[order]
    last = np.ones(p, dtype=bool)
    last[:-1] = (sb[1:] != sb[:-1]) | (ss[1:] != ss[:-1])
    keep = np.zeros(p, dtype=bool)
    keep[order] = last
    return keep


def _bin_quantize_dedup(table: SpectraTable, config):
    """Shared K1 pack-time pass: f64 quantization (``quantize
    .bin_mean_bins`` — the single grid implementation, da or ppm), range
    filter, and duplicate-(member, bin) drop.  Returns (bins64, kept_src,
    kept_counts, kept_offsets, kept_totals)."""
    from specpride_tpu.ops import quantize

    mz = table.mz
    n_bins = config.n_bins
    bins64, in_range = quantize.bin_mean_bins(mz, config)
    bins64 = np.where(in_range, np.clip(bins64, 0, n_bins - 1), -1)
    spec_of_peak = np.repeat(
        np.arange(table.n_spectra, dtype=np.int64), table.peak_counts
    )
    keep = _dedup_keep_mask(spec_of_peak, bins64, mz) & in_range

    # kept-peak table view: rebuild per-spectrum offsets over kept peaks
    kept_counts = np.bincount(
        spec_of_peak[keep], minlength=table.n_spectra
    ).astype(np.int64)
    kept_offsets = np.zeros(table.n_spectra + 1, dtype=np.int64)
    np.cumsum(kept_counts, out=kept_offsets[1:])
    kept_src = np.flatnonzero(keep)  # kept-peak -> original peak

    kept_totals = np.bincount(
        table.cluster_code, weights=kept_counts, minlength=table.n_clusters
    ).astype(np.int64)
    return bins64, kept_src, kept_counts, kept_offsets, kept_totals


@tracing.traced("pack:bucketize_bin_mean")
def pack_bucketize_bin_mean(
    clusters_or_table,
    bin_config,
    config: BatchConfig = BatchConfig(),
) -> list[BinPackedBatch]:
    """Quantize (float64), dedup, and bucket clusters for the binned-mean
    kernel.  K buckets are chosen on the DEDUPED, range-filtered peak
    counts.  One vectorized pass over the whole table."""
    table = _as_table(clusters_or_table)
    idx = table.cluster_order()

    mz = table.mz
    bins64, kept_src, kept_counts, kept_offsets, kept_totals = (
        _bin_quantize_dedup(table, bin_config)
    )

    eligible = idx.n_members > 0
    plans = _plan_buckets(idx, eligible, kept_totals, config, False)

    # a lightweight "table" over kept peaks drives the same layout helper
    kept_table = dataclasses.replace(
        table,
        mz=table.mz,  # unused by _peak_layout beyond indexing via offsets
        peak_offsets=kept_offsets,
    )
    kept_idx = dataclasses.replace(idx, total_peaks=kept_totals)

    batches: list[BinPackedBatch] = []
    for plan in plans:
        codes = plan.codes
        b, k = codes.size, plan.k
        _, _, _, _, src_kept, dest = _peak_layout(kept_table, kept_idx, plan)
        src = kept_src[src_kept]
        mzf = np.zeros(b * k, dtype=np.float32)
        mzf[dest] = mz[src]
        inten = np.zeros(b * k, dtype=np.float32)
        inten[dest] = table.intensity[src]
        pbins = np.full(b * k, bin_config.n_bins, dtype=np.int32)
        pbins[dest] = bins64[src]
        # pre-sort each row by bin ON THE HOST (sentinel n_bins sorts the
        # padding last): the device kernel's per-row stable argsort was the
        # dominant device cost — TPU sorts are slow, host take_along_axis
        # is one vector pass.  Segment sums are order-insensitive within a
        # bin, so stable order preserves kernel semantics exactly.
        mzf = mzf.reshape(b, k)
        inten = inten.reshape(b, k)
        pbins = pbins.reshape(b, k)
        order = np.argsort(pbins, axis=1, kind="stable")
        batches.append(
            BinPackedBatch(
                mz=np.take_along_axis(mzf, order, axis=1),
                intensity=np.take_along_axis(inten, order, axis=1),
                bins=np.take_along_axis(pbins, order, axis=1),
                n_valid=kept_totals[codes].astype(np.int32),
                n_members=idx.n_members[codes].astype(np.int32),
                cluster_ids=[table.cluster_names[c] for c in codes],
                source_indices=[int(c) for c in codes],
            )
        )
    return batches


# ---------------------------------------------------------------------------
# Flat ragged binned-mean packing (K1, zero padding)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FlatBinBatch:
    """Zero-padding flat layout for the binned-mean kernel: every kept peak
    of every cluster concatenated along ONE axis, sorted by (cluster, bin).

    The padded (B, K) bucket layout wastes ~50% of H2D bytes on bucket
    padding with realistic gamma-skewed cluster sizes; on tunneled hosts
    the link is also latency-bound (~0.1 s per transfer round trip,
    H2D ~1.4 GB/s vs D2H ~25 MB/s measured).  The flat layout ships exactly
    the kept peaks — the only padding is a pow2 tail on N (one XLA compile
    per size class).  Not mesh-shardable (peak-axis sharding would split
    clusters across devices); the mesh path keeps the (B, K) layout.

    ``gbin`` composites (local_row, bin) into one int32 so the device kernel
    needs no separate row channel: ``local_row * (n_bins + 1) + bin``, with
    int32-max as the tail sentinel.  Rows are chunk-local; ``rows``
    clusters per chunk are bounded so the composite fits int32.
    """

    mz: np.ndarray  # (N,) f32, sorted by (cluster, bin)
    intensity: np.ndarray  # (N,) f32, same order
    gbin: np.ndarray  # (N,) i32 composite, sentinel = 2**31 - 1
    n_members: np.ndarray  # (rows,) i32
    n_distinct_total: int  # exact surviving-bin bound for this chunk
    run_starts: np.ndarray  # (R,) i64 run-start positions within the chunk
    cluster_ids: list[str]
    source_indices: list[int]
    # reduced-precision packed path (--precision {f32,bf16,int8}): the
    # encoded intensity channel the DEVICE flat path ships instead of
    # f32 — bf16 codes, or int8 codes against a per-cluster ``scale``
    # the host applies to the fetched means (scale never crosses the
    # link).  f32 runs leave all three at their defaults; the f32
    # ``intensity`` stays for the host paths and byte-parity oracle.
    precision: str = "f32"
    codes: np.ndarray | None = None  # (N,) bf16 | int8
    scale: np.ndarray | None = None  # (rows,) f32, int8 only


@tracing.traced("pack:flat_bin_mean")
def pack_flat_bin_mean(
    clusters_or_table,
    bin_config,
    max_elements: int = 16 * 1024 * 1024,
    precision: str = "f32",
) -> list[FlatBinBatch]:
    """Quantize (f64), dedup, and lay out ALL kept peaks flat, sorted by
    (cluster, bin) — one vectorized pass, no buckets, no per-row padding.
    Chunked so each batch holds <= ``max_elements`` peaks and the (row, bin)
    composite stays inside int32.

    ``precision`` != "f32" additionally quantizes the intensity channel AT
    PACK TIME (``ops.quantize.encode_intensity_flat``) into per-chunk
    ``codes`` (+ per-cluster int8 ``scale``) for the reduced-precision
    device flat path; f32 is a strict identity — byte-parity guaranteed."""
    table = _as_table(clusters_or_table)
    idx = table.cluster_order()
    n_bins = bin_config.n_bins

    bins64, kept_src, kept_counts, kept_offsets, kept_totals = (
        _bin_quantize_dedup(table, bin_config)
    )

    c = table.n_clusters
    row_peak_offsets = np.zeros(c + 1, dtype=np.int64)
    np.cumsum(kept_totals, out=row_peak_offsets[1:])

    # group kept peaks by cluster, then sort each cluster's peaks by bin
    # with the segmented sorter (multithreaded native when built; clusters
    # are independent segments, so a global lexsort wastes the structure)
    from specpride_tpu.ops.segsort import seg_argsort

    if np.array_equal(idx.order, np.arange(idx.order.size)):
        # spectra already cluster-contiguous (the common CLI case): kept
        # peaks are already grouped by cluster in kept_src order
        orig = kept_src
    else:
        cnt_kept = kept_counts[idx.order]
        src2 = np.repeat(
            kept_offsets[idx.order], cnt_kept
        ) + _grouped_arange(cnt_kept)
        orig = kept_src[src2]  # original peak ids, grouped by cluster
    order_local = seg_argsort(bins64[orig], row_peak_offsets)
    final = orig[order_local]
    s_mz = table.mz[final].astype(np.float32)
    s_int = table.intensity[final].astype(np.float32)
    s_bin = bins64[final]
    s_row = np.repeat(np.arange(c, dtype=np.int64), kept_totals)

    # run starts over the sorted (row, bin) axis: the exact compaction
    # bound AND the run structure the backend's host pass consumes
    # (carried per chunk so nothing re-derives it)
    if s_bin.size:
        first = np.ones(s_bin.size, dtype=bool)
        first[1:] = (s_bin[1:] != s_bin[:-1]) | (s_row[1:] != s_row[:-1])
    else:
        first = np.zeros(0, dtype=bool)

    # chunk rows greedily under the element and composite-key budgets
    max_rows = (2**31 - 2) // (n_bins + 1)
    batches: list[FlatBinBatch] = []
    lo = 0
    while lo < c:
        hi = min(lo + max_rows, c)
        # shrink until the peak count fits
        while (
            hi > lo + 1
            and row_peak_offsets[hi] - row_peak_offsets[lo] > max_elements
        ):
            hi = lo + int(
                np.searchsorted(
                    row_peak_offsets[lo + 1 : hi + 1],
                    row_peak_offsets[lo] + max_elements,
                    side="right",
                )
            )
            hi = max(hi, lo + 1)
        p0, p1 = int(row_peak_offsets[lo]), int(row_peak_offsets[hi])
        gbin = (
            (s_row[p0:p1] - lo) * np.int64(n_bins + 1) + s_bin[p0:p1]
        ).astype(np.int32)
        # chunk boundaries are row boundaries, so first[p0] is always a
        # run start — chunk-local positions need no fixup
        run_starts = np.flatnonzero(first[p0:p1])
        codes = scale = None
        if precision != "f32":
            from specpride_tpu.ops import quantize

            codes, scale = quantize.encode_intensity_flat(
                s_int[p0:p1], row_peak_offsets[lo : hi + 1] - p0, precision
            )
        batches.append(
            FlatBinBatch(
                mz=s_mz[p0:p1],
                intensity=s_int[p0:p1],
                gbin=gbin,
                n_members=idx.n_members[lo:hi].astype(np.int32),
                n_distinct_total=int(run_starts.size),
                run_starts=run_starts,
                cluster_ids=[table.cluster_names[i] for i in range(lo, hi)],
                source_indices=list(range(lo, hi)),
                precision=precision,
                codes=codes,
                scale=scale,
            )
        )
        lo = hi
    return batches


# ---------------------------------------------------------------------------
# Gap-average packing (K3): f64 sort + gap segments, all vectorized
# ---------------------------------------------------------------------------


@tracing.traced("pack:gap_segments")
def gap_global_segments(table, idx, config) -> dict:
    """Sort + f64 gap-segment EVERY cluster in one vectorized global pass
    (same grouping semantics as ``ops.quantize.gap_segments`` — the numpy
    oracle's per-cluster code path — validated against it by the parity
    suite).  Shared by the bucketized device packer and the vectorized
    host consensus (``backends.tpu_backend.TpuBackend.run_gap_average``).

    One global lexsort groups peaks by cluster and orders them by m/z
    (singleton clusters order by input position instead, ref :88-90
    passthrough); gap booleans, the reference's final-gap merge
    (``tail_mode="reference"``), and segment ids all come from flat
    cumsum/bincount passes."""
    p_total = table.n_peaks
    spec_of_peak = np.repeat(
        np.arange(table.n_spectra, dtype=np.int64), table.peak_counts
    )
    cluster_of_peak = table.cluster_code[spec_of_peak]
    nm_of_peak = idx.n_members[cluster_of_peak]

    # sort key: m/z for multi-member clusters, input position for singletons
    # (positions are small integers — exact in f64)
    key = np.where(
        nm_of_peak == 1, np.arange(p_total, dtype=np.float64), table.mz
    )
    order = np.lexsort((key, cluster_of_peak))
    s_cluster = cluster_of_peak[order]
    s_mz = table.mz[order]

    same_cluster = np.zeros(p_total, dtype=bool)
    if p_total > 1:
        same_cluster[1:] = s_cluster[1:] == s_cluster[:-1]
    gap = np.zeros(p_total, dtype=bool)  # gap[i]: boundary BEFORE peak i
    if p_total > 1:
        diff_ok = (s_mz[1:] - s_mz[:-1]) >= config.mz_accuracy
        gap[1:] = same_cluster[1:] & diff_ok
        # singletons: every peak its own group regardless of spacing
        gap[1:] |= same_cluster[1:] & (idx.n_members[s_cluster[1:]] == 1)

    if config.tail_mode == "reference":
        # drop each multi-member cluster's final gap when it has >= 2 gaps
        # (ref :79-87 iterates ind_list[1:-1])
        gpos = np.flatnonzero(gap)
        if gpos.size:
            gcluster = s_cluster[gpos]
            counts = np.bincount(gcluster, minlength=table.n_clusters)
            is_last = np.ones(gpos.size, dtype=bool)
            is_last[:-1] = gcluster[1:] != gcluster[:-1]
            drop = (
                is_last
                & (counts[gcluster] >= 2)
                & (idx.n_members[gcluster] > 1)
            )
            gap[gpos[drop]] = False

    # segment ids, reset at cluster starts
    gseg = np.cumsum(gap)
    cluster_first_peak = np.zeros(p_total, dtype=bool)
    if p_total:
        cluster_first_peak[0] = True
        cluster_first_peak[1:] = ~same_cluster[1:]
    first_pos = np.zeros(table.n_clusters, dtype=np.int64)
    fidx = np.flatnonzero(cluster_first_peak)
    first_pos[s_cluster[fidx]] = fidx
    seg = (gseg - gseg[first_pos[s_cluster]]).astype(np.int32)

    n_groups = np.zeros(table.n_clusters, dtype=np.int64)
    if p_total:
        last_peak = np.ones(p_total, dtype=bool)
        last_peak[:-1] = ~same_cluster[1:]
        lidx = np.flatnonzero(last_peak)
        n_groups[s_cluster[lidx]] = seg[lidx] + 1

    return dict(
        order=order, s_cluster=s_cluster, s_mz=s_mz, gap=gap, seg=seg,
        n_groups=n_groups, first_pos=first_pos,
        cluster_first_peak=cluster_first_peak,
    )


@tracing.traced("pack:bucketize_gap")
def pack_bucketize_gap(
    clusters_or_table,
    config,
    batch_config: BatchConfig = BatchConfig(),
) -> list[GapPackedBatch]:
    """Bucketize the global f64 gap segmentation (``gap_global_segments``)
    by total peak count for ``ops.gap_average.gap_average_compact``."""
    table = _as_table(clusters_or_table)
    idx = table.cluster_order()

    g = gap_global_segments(table, idx, config)
    order, s_mz, seg, n_groups, first_pos = (
        g["order"], g["s_mz"], g["seg"], g["n_groups"], g["first_pos"]
    )

    quorum_all = np.ceil(
        config.min_fraction * idx.n_members.astype(np.float64)
    ).astype(np.int32)

    eligible = idx.n_members > 0
    plans = _plan_buckets(idx, eligible, idx.total_peaks, batch_config, False)

    # per-cluster start of its sorted-peak block, for the (B, K) scatter
    batches: list[GapPackedBatch] = []
    s_intensity = table.intensity[order]
    for plan in plans:
        codes = plan.codes
        b, k = codes.size, plan.k
        totals = idx.total_peaks[codes]
        src = np.repeat(first_pos[codes], totals) + _grouped_arange(totals)
        dest = np.repeat(
            np.arange(b, dtype=np.int64) * k, totals
        ) + _grouped_arange(totals)
        mzf = np.zeros(b * k, dtype=np.float32)
        mzf[dest] = s_mz[src]
        inten = np.zeros(b * k, dtype=np.float32)
        inten[dest] = s_intensity[src]
        pseg = np.zeros(b * k, dtype=np.int32)
        pseg[dest] = seg[src]
        batches.append(
            GapPackedBatch(
                mz=mzf.reshape(b, k),
                intensity=inten.reshape(b, k),
                seg=pseg.reshape(b, k),
                n_valid=totals.astype(np.int32),
                quorum=quorum_all[codes],
                n_members=idx.n_members[codes].astype(np.int32),
                n_groups=n_groups[codes],
                cluster_ids=[table.cluster_names[c] for c in codes],
                source_indices=[int(c) for c in codes],
            )
        )
    return batches
