"""Host-side peak data model.

The reference passes spectra around as pyteomics-style dicts of numpy arrays
(`'m/z array'`, `'intensity array'`, precursor fields — ref
src/binning.py:98-103, src/average_spectrum_clustering.py:100-103).  Here the
unit is an immutable ``Spectrum`` with contiguous float32/float64 arrays, and
a ``Cluster`` groups members; both are host-side staging types — device
compute happens on ``specpride_tpu.data.packed`` batch tensors.

Title convention for the clustered-MGF interchange format
(ref file_formats.md:5-9): ``TITLE=<cluster_id>;<usi>`` where the USI is
``mzspec:<PX>:<raw>:scan:<n>[:<PEPTIDE>/<z>]``.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, Mapping

import numpy as np


def parse_title(title: str) -> tuple[str, str]:
    """Split an MGF TITLE into (cluster_id, usi).

    Reference behaviour: split on the first ';'
    (ref src/binning.py:143-144, src/average_spectrum_clustering.py:124-125).
    A title without ';' is a bare cluster id with an empty USI
    (consensus spectra — ref file_formats.md:57).
    """
    cluster_id, sep, usi = title.partition(";")
    return cluster_id, usi


def build_title(
    cluster_id: str,
    px_accession: str,
    raw_name: str,
    scan: int,
    peptide: str | None = None,
    charge: int | None = None,
) -> str:
    """Build the clustered-MGF TITLE (ref src/convert_mgf_cluster.py:14-18).

    The reference function is named ``buid_usi_accession`` (typo); the
    behaviour is reproduced, the name fixed (survey "known bugs" list).
    """
    usi = f"mzspec:{px_accession}:{raw_name}:scan:{scan}"
    if peptide is not None:
        usi = f"{usi}:{peptide}/{charge}"
    return f"{cluster_id};{usi}"


def scan_from_usi(usi: str) -> int | None:
    """Extract the scan number from a USI, or None if absent."""
    parts = usi.split(":")
    for i, part in enumerate(parts):
        if part == "scan" and i + 1 < len(parts):
            try:
                return int(parts[i + 1])
            except ValueError:
                return None
    return None


def peptide_from_usi(usi: str) -> tuple[str | None, int | None]:
    """Extract (peptide, charge) from a USI interpretation suffix, if any."""
    parts = usi.split(":")
    if len(parts) >= 6 and "/" in parts[-1]:
        pep, _, z = parts[-1].rpartition("/")
        try:
            return pep, int(z)
        except ValueError:
            return None, None
    return None, None


@dataclasses.dataclass
class Spectrum:
    """One MS/MS spectrum: parallel m/z / intensity arrays + precursor info."""

    mz: np.ndarray
    intensity: np.ndarray
    precursor_mz: float = 0.0
    precursor_charge: int = 0
    rt: float = 0.0
    title: str = ""
    extra: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        self.mz = np.asarray(self.mz, dtype=np.float64)
        self.intensity = np.asarray(self.intensity, dtype=np.float64)
        if self.mz.shape != self.intensity.shape:
            raise ValueError(
                f"mz and intensity must have equal length, got "
                f"{self.mz.shape} vs {self.intensity.shape}"
            )

    @property
    def n_peaks(self) -> int:
        return int(self.mz.size)

    @property
    def cluster_id(self) -> str:
        return parse_title(self.title)[0]

    @property
    def usi(self) -> str:
        return parse_title(self.title)[1]

    @property
    def neutral_mass(self) -> float:
        """Neutral (uncharged) precursor mass: m*z - z*H
        (ref src/average_spectrum_clustering.py:134-138)."""
        from specpride_tpu.ops.fragments import PROTON_MASS

        z = self.precursor_charge
        return self.precursor_mz * z - z * PROTON_MASS

    @classmethod
    def from_dict(cls, d: Mapping) -> "Spectrum":
        """Accept a pyteomics-style dict ('m/z array', 'params', ...)."""
        params = d.get("params", {})
        pepmass = params.get("pepmass", (0.0,))
        if isinstance(pepmass, (int, float)):
            pepmass = (pepmass,)
        charge = params.get("charge", (0,))
        if isinstance(charge, int):
            charge = (charge,)
        return cls(
            mz=d["m/z array"],
            intensity=d["intensity array"],
            precursor_mz=float(pepmass[0]) if pepmass else 0.0,
            precursor_charge=int(charge[0]) if charge else 0,
            rt=float(params.get("rtinseconds", 0.0) or 0.0),
            title=str(params.get("title", "")),
        )

    def to_dict(self) -> dict:
        """Export as a pyteomics-style dict (for interop / MGF writing)."""
        return {
            "m/z array": self.mz,
            "intensity array": self.intensity,
            "params": {
                "title": self.title,
                "pepmass": (self.precursor_mz,),
                "charge": (self.precursor_charge,),
                "rtinseconds": self.rt,
            },
        }


@dataclasses.dataclass
class Cluster:
    """A cluster of member spectra sharing a cluster id."""

    cluster_id: str
    members: list[Spectrum]

    @property
    def n_members(self) -> int:
        return len(self.members)

    @property
    def total_peaks(self) -> int:
        return sum(s.n_peaks for s in self.members)

    @property
    def max_peaks(self) -> int:
        return max((s.n_peaks for s in self.members), default=0)

    def __iter__(self) -> Iterator[Spectrum]:
        return iter(self.members)

    def __len__(self) -> int:
        return len(self.members)


def group_into_clusters(spectra: Iterable[Spectrum]) -> list[Cluster]:
    """Group spectra by the cluster id encoded in their titles, preserving
    first-seen cluster order and in-file member order
    (ref src/binning.py:159-165, src/best_spectrum.py:144-148)."""
    by_id: dict[str, list[Spectrum]] = {}
    for s in spectra:
        by_id.setdefault(s.cluster_id, []).append(s)
    return [Cluster(cid, members) for cid, members in by_id.items()]
