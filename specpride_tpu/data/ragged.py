"""Ragged clusters → bucketed, padded device batches.

The compute core operates on dense ``(cluster, member, peak)`` tensors with
validity masks.  Peaks per spectrum and members per cluster vary wildly
(survey §7 hard part a), so clusters are bucketed by padded (member, peak)
size: each distinct bucket shape is one XLA compilation, and padding waste is
bounded by the bucket granularity.

The reference has no equivalent — it loops Python lists of dicts
(ref src/binning.py:291-297).  This module is the boundary where the host
data model becomes an HBM-resident ragged tensor (BASELINE.json north star).
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Iterable, Sequence

import numpy as np

from specpride_tpu.config import BatchConfig
from specpride_tpu.data.peaks import Cluster


@dataclasses.dataclass
class ClusterBatch:
    """A dense batch of B clusters, each padded to M members × P peaks.

    Numpy-backed on the host; becomes device-resident (and shardable along
    the leading cluster axis) when passed into a jitted kernel.  Padding
    convention: invalid peaks have mz = 0, intensity = 0, mask False;
    invalid members have n_peaks = 0 and member_mask False.
    """

    mz: np.ndarray  # (B, M, P) float32
    mz64: np.ndarray  # (B, M, P) float64 — HOST-ONLY: exact m/z for f64
    # quantization (ops.quantize); never shipped to device
    intensity: np.ndarray  # (B, M, P) float32
    peak_mask: np.ndarray  # (B, M, P) bool
    member_mask: np.ndarray  # (B, M) bool
    precursor_mz: np.ndarray  # (B, M) float32
    precursor_charge: np.ndarray  # (B, M) int32
    rt: np.ndarray  # (B, M) float32
    n_members: np.ndarray  # (B,) int32
    n_peaks: np.ndarray  # (B, M) int32
    cluster_ids: list[str]  # length B (host-only metadata)
    source_indices: list[int] = dataclasses.field(default_factory=list)
    # position of each cluster in the caller's original sequence (host-only;
    # lets drivers reassemble bucket-shuffled outputs into input order)

    @property
    def shape(self) -> tuple[int, int, int]:
        return self.mz.shape  # type: ignore[return-value]

    @property
    def n_clusters(self) -> int:
        return self.mz.shape[0]

    def device_arrays(self) -> dict[str, np.ndarray]:
        """The tensors that participate in device compute (no host metadata)."""
        return {
            "mz": self.mz,
            "intensity": self.intensity,
            "peak_mask": self.peak_mask,
            "member_mask": self.member_mask,
            "precursor_mz": self.precursor_mz,
            "precursor_charge": self.precursor_charge,
            "rt": self.rt,
            "n_members": self.n_members,
            "n_peaks": self.n_peaks,
        }


def _bucket_for(value: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= value; the largest bucket if value exceeds all."""
    i = bisect.bisect_left(buckets, value)
    return buckets[min(i, len(buckets) - 1)]


def pad_clusters(
    clusters: Sequence[Cluster],
    n_members: int,
    n_peaks: int,
    source_indices: Sequence[int] | None = None,
) -> ClusterBatch:
    """Pad a homogeneous group of clusters into one dense ClusterBatch."""
    b = len(clusters)
    mz = np.zeros((b, n_members, n_peaks), dtype=np.float32)
    mz64 = np.zeros((b, n_members, n_peaks), dtype=np.float64)
    intensity = np.zeros((b, n_members, n_peaks), dtype=np.float32)
    peak_mask = np.zeros((b, n_members, n_peaks), dtype=bool)
    member_mask = np.zeros((b, n_members), dtype=bool)
    precursor_mz = np.zeros((b, n_members), dtype=np.float32)
    precursor_charge = np.zeros((b, n_members), dtype=np.int32)
    rt = np.zeros((b, n_members), dtype=np.float32)
    n_members_arr = np.zeros((b,), dtype=np.int32)
    n_peaks_arr = np.zeros((b, n_members), dtype=np.int32)

    for ci, cluster in enumerate(clusters):
        if cluster.n_members > n_members:
            raise ValueError(
                f"cluster {cluster.cluster_id} has {cluster.n_members} "
                f"members > member bucket {n_members}"
            )
        n_members_arr[ci] = cluster.n_members
        for mi, s in enumerate(cluster.members):
            k = s.n_peaks
            if k > n_peaks:
                raise ValueError(
                    f"cluster {cluster.cluster_id} member {mi} has {s.n_peaks} "
                    f"peaks > peak bucket {n_peaks}"
                )
            mz[ci, mi, :k] = s.mz[:k]
            mz64[ci, mi, :k] = s.mz[:k]
            intensity[ci, mi, :k] = s.intensity[:k]
            peak_mask[ci, mi, :k] = True
            member_mask[ci, mi] = True
            precursor_mz[ci, mi] = s.precursor_mz
            precursor_charge[ci, mi] = s.precursor_charge
            rt[ci, mi] = s.rt
            n_peaks_arr[ci, mi] = k

    return ClusterBatch(
        mz=mz,
        mz64=mz64,
        intensity=intensity,
        peak_mask=peak_mask,
        member_mask=member_mask,
        precursor_mz=precursor_mz,
        precursor_charge=precursor_charge,
        rt=rt,
        n_members=n_members_arr,
        n_peaks=n_peaks_arr,
        cluster_ids=[c.cluster_id for c in clusters],
        source_indices=(
            list(source_indices) if source_indices is not None else list(range(b))
        ),
    )


def bucketize_clusters(
    clusters: Iterable[Cluster],
    config: BatchConfig = BatchConfig(),
) -> list[ClusterBatch]:
    """Group clusters into padded batches of homogeneous (M, P) bucket shape.

    Singleton clusters (n_members == 1) are bucketed too: every kernel has a
    defined singleton behaviour (passthrough — ref
    src/average_spectrum_clustering.py:88-90,
    src/most_similar_representative.py:79-81), so they ride the same path.
    Order within a bucket is preserved; each batch records the position of
    its clusters in the input sequence (``ClusterBatch.source_indices``) so
    callers can reassemble outputs into input order.
    """
    buckets: dict[tuple[int, int], list[tuple[int, Cluster]]] = {}
    for i, c in enumerate(clusters):
        if c.n_members == 0:
            continue
        mkey = _bucket_for(c.n_members, config.member_buckets)
        pkey = _bucket_for(max(c.max_peaks, 1), config.peak_buckets)
        if c.n_members > mkey:
            # exceeds the largest member bucket: grow to the next power of two
            mkey = 1 << (c.n_members - 1).bit_length()
        if c.max_peaks > pkey:
            pkey = 1 << (c.max_peaks - 1).bit_length()
        buckets.setdefault((mkey, pkey), []).append((i, c))

    batches: list[ClusterBatch] = []
    for (mkey, pkey), group in buckets.items():
        for start in range(0, len(group), config.clusters_per_batch):
            chunk = group[start : start + config.clusters_per_batch]
            batches.append(
                pad_clusters(
                    [c for _, c in chunk], mkey, pkey, [i for i, _ in chunk]
                )
            )
    return batches
