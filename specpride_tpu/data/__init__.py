from specpride_tpu.data.peaks import Spectrum, Cluster, parse_title, build_title
from specpride_tpu.data.ragged import ClusterBatch, bucketize_clusters

__all__ = [
    "Spectrum",
    "Cluster",
    "parse_title",
    "build_title",
    "ClusterBatch",
    "bucketize_clusters",
]
