"""Host data model: spectra/clusters + packed device batches."""
from specpride_tpu.data.peaks import Cluster, Spectrum, group_into_clusters
from specpride_tpu.data.packed import (
    BinPackedBatch,
    PackedBatch,
    pack_bucketize,
    pack_bucketize_bin_mean,
)

__all__ = [
    "Cluster",
    "Spectrum",
    "group_into_clusters",
    "PackedBatch",
    "BinPackedBatch",
    "pack_bucketize",
    "pack_bucketize_bin_mean",
]
