"""One error taxonomy for the whole execution stack.

The executor's recovery decisions hang off three classes:

* **transient** — worth retrying in place: I/O hiccups (``OSError``),
  device resource pressure, lane hangs broken by the watchdog.  These
  are the failures a production host sees under load and that a bounded
  backoff genuinely fixes.
* **oom** — a ``RESOURCE_EXHAUSTED`` device allocation failure.  A
  retry of the *same* batch usually fails again, but a *smaller* batch
  fits: the executor splits the chunk in half instead of retrying.
* **permanent** — malformed input, logic errors (``ValueError``):
  retrying cannot help, so they surface straight to ``--on-error``.

Real device OOMs arrive as ``jaxlib``'s ``XlaRuntimeError`` whose
message starts with the gRPC status name — matched here by substring so
this module never imports jax (the numpy oracle path must load without
it).  Injected faults raise the same shapes (``faults.py``), so the
classification path exercised in tests is the one production hits.
"""

from __future__ import annotations

# substrings of RuntimeError messages that mark a device allocation
# failure (jaxlib XlaRuntimeError carries the gRPC status name verbatim)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")

# RuntimeError messages that mark *transient* device/runtime trouble
# worth a retry (collective timeouts, preempted devices, poisoned
# streams after a neighboring failure)
_TRANSIENT_MARKERS = (
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "CANCELLED",
    "INTERNAL: Failed to",
)


class InjectedFault(Exception):
    """Mixin marking an exception as injected by a FaultPlan — never
    raised directly; concrete faults subclass (kind, real type)."""


class LaneHangError(TimeoutError):
    """A lane section stalled past the watchdog timeout (or an injected
    ``hang`` ran out its bound).  Transient: the work itself is intact,
    so the enclosing retry re-runs it."""


class LeaseExpiredError(RuntimeError):
    """This rank lost its lease on an elastic chunk range — another rank
    observed the lease expired (a stall past the TTL) and took the range
    over.  **Permanent** by design: retrying the commit would race the
    new holder on the same part file, so the loser must abandon the
    range and claim fresh work instead.  (``is_transient`` stays False
    because the message carries none of the transient/OOM markers.)"""


def is_oom(exc: BaseException) -> bool:
    """Device allocation failure — the degradation (chunk-split) class."""
    return isinstance(exc, RuntimeError) and any(
        m in str(exc) for m in _OOM_MARKERS
    )


def is_transient(exc: BaseException) -> bool:
    """Worth retrying in place.  OOM is also transient in the taxonomy —
    when the caller cannot split (single-cluster chunk, ``--no-degrade``)
    a backoff retry is the only remaining in-place recovery."""
    if isinstance(exc, (OSError, TimeoutError)):
        return True
    if is_oom(exc):
        return True
    return isinstance(exc, RuntimeError) and any(
        m in str(exc) for m in _TRANSIENT_MARKERS
    )


def classify(exc: BaseException) -> str:
    """``"oom"`` | ``"transient"`` | ``"permanent"`` — the order matters:
    OOM is transient too, but callers that can degrade check it first."""
    if is_oom(exc):
        return "oom"
    if is_transient(exc):
        return "transient"
    return "permanent"
