"""Malformed-record quarantine: divert unparseable MGF blocks instead
of aborting the run.

A truncated ``BEGIN IONS`` block (a torn upload, a corrupted stripe) or
a record whose peak lines don't parse used to kill a whole
million-spectrum run at whatever point the parser reached it.  Under
``--on-error skip`` the parsers now hand such blocks to a
:class:`Quarantine`, which appends the raw text verbatim to
``<output>.quarantine.mgf`` (lazily created — no faults, no file) and
journals a ``quarantine`` event per block, so the dropped records are
recoverable and auditable rather than silently skipped or fatally
raised.

Thread-safe: streamed-window parsing happens on pack-pool workers.
Blocks found before the run journal opens (the eager parse runs first)
are buffered and flushed when :meth:`bind` attaches the journal.
"""

from __future__ import annotations

import contextlib
import os
import threading

from specpride_tpu.observability import logger


class Quarantine:
    def __init__(self, path: str):
        self.path = str(path)
        self.count = 0
        self._lock = threading.Lock()
        self._journal = None
        self._pending: list[dict] = []
        self._fh = None
        # per-run semantics: a resume re-parses the whole input and
        # re-quarantines every bad block, so a surviving file from an
        # earlier attempt would only accumulate duplicates (and a stale
        # file from an unrelated run at the same output path would lie)
        with contextlib.suppress(OSError):
            os.remove(self.path)

    def rename(self, path: str) -> None:
        """Move the quarantine to a new path (multi-host sharding gives
        each rank its own ``.part<id>`` file, like every other per-run
        artifact).  Safe before or after the first block landed."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            if os.path.exists(self.path):
                os.replace(self.path, str(path))
            self.path = str(path)

    def bind(self, journal) -> None:
        """Attach the run journal; events queued before it opened flush
        now (journal consumers still see them after run_start)."""
        with self._lock:
            self._journal = journal
            pending, self._pending = self._pending, []
        for fields in pending:
            journal.emit("quarantine", **fields)

    def add(self, raw: str, reason: str) -> None:
        """Append one malformed block to the quarantine file.  Matches
        the ``malformed`` callback signature of ``io.mgf``'s parsers."""
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
            text = raw if raw.endswith("\n") else raw + "\n"
            self._fh.write(text)
            if not text.endswith("\n\n"):
                self._fh.write("\n")
            self._fh.flush()
            self.count += 1
            journal = self._journal
            fields = {
                "path": self.path, "reason": reason,
                "n_bytes": len(raw),
            }
            if journal is None:
                self._pending.append(fields)
        logger.warning(
            "quarantined malformed MGF block (%s) -> %s", reason, self.path
        )
        if journal is not None:
            journal.emit("quarantine", **fields)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
