"""Fault-injection, retry, degradation and integrity layer.

Production-scale MS pipelines stream millions of spectra through hosts
and accelerators where transient failures are routine (SpecHD,
arXiv:2311.12874 targets exactly such hardware; clustering at the scale
of arXiv:1301.0834 makes "restart the run" an unaffordable recovery
strategy).  This package makes every lane boundary of the multi-lane
chunk executor (``cli._checkpointed_run``) a *recoverable* failure
point, and makes the recovery paths themselves testable:

``faults``
    A seeded, deterministic :class:`FaultPlan` (``--inject-faults``
    ``SITE:KIND:RATE[:AFTER[:MAX]]``, or the ``SPECPRIDE_FAULTS`` env
    var for subprocess tests) fires realistic errors at named sites
    already delimited by tracing spans — ``parse``, ``pack``,
    ``prepare``, ``dispatch``, ``d2h``, ``qc``, ``write``,
    ``checkpoint_write``.  Every injected fault is journaled.

``errors``
    One error taxonomy both backends and the executor share:
    transient (worth retrying), out-of-memory (worth degrading), or
    permanent (surface to ``--on-error``).

``retry``
    Bounded exponential backoff with deterministic jitter
    (``--retries`` / ``--retry-backoff``) around chunk dispatch and the
    committer's write+checkpoint tail; every retry is journaled and
    counted into ``run_end.robustness``.

``watchdog``
    A per-lane stall monitor (``--watchdog-timeout``): lanes run their
    work inside watched sections; a section that exceeds the timeout is
    journaled as ``watchdog_stall`` and cancels any injected ``hang``
    so the lane's retry policy can recover it.

``integrity``
    Checkpoint manifests gain a schema version and a sha256 of the
    committed MGF bytes; resume verifies the hash, truncates torn
    tails at record boundaries, and journals every ``resume_repair``.

``quarantine``
    Malformed MGF records divert to ``<output>.quarantine.mgf``
    instead of aborting the run (under ``--on-error skip``).
"""

from specpride_tpu.robustness.errors import (  # noqa: F401
    InjectedFault,
    LaneHangError,
    classify,
    is_oom,
    is_transient,
)
from specpride_tpu.robustness.faults import (  # noqa: F401
    FAULT_SITES,
    FaultPlan,
    active_plan,
    check,
    install,
    recovery_sites_for,
    uninstall,
)
from specpride_tpu.robustness.harness import Harness  # noqa: F401
from specpride_tpu.robustness.integrity import (  # noqa: F401
    MANIFEST_SCHEMA,
    OutputIntegrity,
)
from specpride_tpu.robustness.quarantine import Quarantine  # noqa: F401
from specpride_tpu.robustness.retry import RetryPolicy  # noqa: F401
from specpride_tpu.robustness.watchdog import Watchdog  # noqa: F401
