"""Checkpoint/output integrity: schema-versioned manifests with a
sha256 over the committed MGF bytes.

The commit protocol (``cli._commit_chunk``) appends chunk *i*'s bytes,
then atomically replaces the manifest recording ``{done, output_bytes,
sha256, schema}``.  The hash covers exactly the first ``output_bytes``
bytes of the output — the committed prefix — maintained incrementally
by :class:`OutputIntegrity` (each commit absorbs only the bytes it just
appended, so hashing cost is O(bytes written), never O(file size) per
chunk).

On resume the manifest's hash is verified against the file in one
O(file) pass that doubles as the re-seed of the running hash.  This
closes the two corruption windows the byte-count check alone misses:
a bit flip *inside* the committed region (count unchanged, data wrong)
and a torn tail that happens to land at the recorded size.  Every
repair decision is journaled as a ``resume_repair`` event.
"""

from __future__ import annotations

import hashlib
import os

# manifest schema: 1 = the implicit legacy {done, output_bytes, failed}
# shape (no version field); 2 adds "schema" + "sha256".  Legacy
# manifests still resume — without a hash there is nothing to verify,
# so they get the historical byte-count checks only.
MANIFEST_SCHEMA = 2

_CHUNK = 1 << 20


class OutputIntegrity:
    """Running sha256 over the committed prefix of one output file."""

    def __init__(self) -> None:
        self._hasher = hashlib.sha256()
        self.offset = 0

    def reset(self) -> None:
        self._hasher = hashlib.sha256()
        self.offset = 0

    def hexdigest(self) -> str:
        return self._hasher.hexdigest()

    def absorb(self, path: str, new_size: int) -> None:
        """Advance the committed prefix to ``new_size`` by hashing the
        bytes appended since the last commit."""
        if new_size <= self.offset:
            return
        with open(path, "rb") as fh:
            fh.seek(self.offset)
            remaining = new_size - self.offset
            while remaining > 0:
                block = fh.read(min(_CHUNK, remaining))
                if not block:
                    break
                self._hasher.update(block)
                remaining -= len(block)
        self.offset = new_size

    def seed_file(self, path: str, upto: int) -> str:
        """(Re)start the running hash from the first ``upto`` bytes of
        ``path`` — the resume/append seeding pass.  Returns the digest
        of that prefix so the caller can verify it against a manifest in
        the same read."""
        self.reset()
        self.absorb(path, upto)
        return self.hexdigest()


def manifest_payload(done, output_bytes: int, integrity: "OutputIntegrity",
                     failed=None) -> dict:
    """The schema-v2 manifest body every checkpoint write emits."""
    return {
        "schema": MANIFEST_SCHEMA,
        "done": sorted(done),
        "output_bytes": output_bytes,
        "sha256": integrity.hexdigest(),
        **({"failed": failed} if failed else {}),
    }
