"""Per-lane stall watchdog (``--watchdog-timeout``).

Each executor lane runs its work inside a watched *section*
(``with watchdog.section("dispatch"): ...``); a monitor thread checks
open sections and, when one exceeds the timeout, journals a
``watchdog_stall`` event and cancels any injected hang so the lane
raises a transient :class:`~specpride_tpu.robustness.errors.LaneHangError`
the retry policy recovers.

Sections — not heartbeats — are the right primitive here: a lane parked
on an empty queue is *idle*, not stalled, and must never trip the
watchdog; only time spent inside real work counts.  Against a genuine
runaway (a wedged device stream, not an injected one) the watchdog
cannot interrupt the thread — Python offers no safe cross-thread
interrupt — but the journaled stall pins *which lane* and *how long*,
which is the information a kill/resume operator needs.
"""

from __future__ import annotations

import itertools
import threading
import time

from specpride_tpu.observability import logger


class Watchdog:
    """Monitor thread over named lane sections.

    ``timeout_s <= 0`` builds a disabled instance whose ``section`` is
    free (no thread, no lock traffic) so call sites never branch."""

    def __init__(self, timeout_s: float, journal=None, on_stall=None):
        self.timeout_s = float(timeout_s)
        self.enabled = self.timeout_s > 0
        self.journal = journal
        self.on_stall = on_stall  # e.g. FaultPlan.cancel_hangs
        self.stall_count = 0
        self._sections: dict[int, tuple[str, float]] = {}
        self._flagged: set[int] = set()
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self.enabled:
            self._thread = threading.Thread(
                target=self._monitor, name="specpride-watchdog", daemon=True
            )
            self._thread.start()

    class _Section:
        __slots__ = ("_wd", "_key")

        def __init__(self, wd: "Watchdog | None", key: int | None):
            self._wd, self._key = wd, key

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            if self._wd is not None:
                with self._wd._lock:
                    self._wd._sections.pop(self._key, None)
                    self._wd._flagged.discard(self._key)

    def section(self, lane: str) -> "_Section":
        """Mark this thread as doing ``lane`` work until exit."""
        if not self.enabled:
            return self._Section(None, None)
        key = next(self._ids)
        with self._lock:
            self._sections[key] = (lane, time.perf_counter())
        return self._Section(self, key)

    def _monitor(self) -> None:
        # poll a few times per timeout so detection latency stays a
        # fraction of the bound without a hot loop
        step = min(max(self.timeout_s / 5.0, 0.02), 0.5)
        while not self._stop.wait(step):
            now = time.perf_counter()
            stalled: list[tuple[str, float]] = []
            with self._lock:
                for key, (lane, t0) in self._sections.items():
                    if key in self._flagged:
                        continue
                    if now - t0 >= self.timeout_s:
                        # flag once per section: a stall is an event,
                        # not a condition to re-report every poll
                        self._flagged.add(key)
                        stalled.append((lane, now - t0))
            for lane, elapsed in stalled:
                self.stall_count += 1
                logger.warning(
                    "lane %s stalled for %.2fs (watchdog timeout %.2fs)",
                    lane, elapsed, self.timeout_s,
                )
                if self.journal is not None:
                    self.journal.emit(
                        "watchdog_stall", lane=lane,
                        elapsed_s=round(elapsed, 4),
                        timeout_s=self.timeout_s,
                    )
                if self.on_stall is not None:
                    self.on_stall()

    def stalled(self) -> list[tuple[str, float]]:
        """Currently-open sections the monitor has flagged as stalled:
        ``(lane, elapsed_s)`` pairs — the live per-lane health view the
        serving daemon's ``/healthz`` readiness probe reports (a section
        that EXITED clears itself, so recovery is visible immediately,
        not at the next poll)."""
        if not self.enabled:
            return []
        now = time.perf_counter()
        with self._lock:
            return [
                (lane, round(now - t0, 3))
                for key, (lane, t0) in self._sections.items()
                if key in self._flagged
            ]

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None
