"""Per-run robustness harness: one object the executor threads through
its lanes instead of four.

Bundles the armed :class:`~specpride_tpu.robustness.faults.FaultPlan`
(if any), the :class:`~specpride_tpu.robustness.retry.RetryPolicy`, the
per-lane :class:`~specpride_tpu.robustness.watchdog.Watchdog`, and the
degradation switch (``--no-degrade``), plus the degrade/repair counters
that land in ``run_end.robustness``.  Construction arms the fault plan
process-globally (backends reach it via ``faults.check``);
:meth:`close` disarms it and stops the watchdog — the CLI pairs the two
in a ``finally`` so an aborted run never leaks an armed plan into the
next in-process invocation (tests and bench nest ``cli_main`` calls).
"""

from __future__ import annotations

import contextlib
import threading

from specpride_tpu.robustness import faults as faults_mod
from specpride_tpu.robustness.faults import FaultPlan
from specpride_tpu.robustness.retry import RetryPolicy
from specpride_tpu.robustness.watchdog import Watchdog


class Harness:
    def __init__(self, plan: FaultPlan | None, policy: RetryPolicy,
                 watchdog: Watchdog | None, degrade: bool, journal=None):
        self.plan = plan
        self.policy = policy
        self.watchdog = watchdog
        self.degrade = degrade
        self.journal = journal
        self._lock = threading.Lock()
        self.degrade_splits = 0
        self.degrade_reroutes = 0
        self.resume_repairs = 0
        self._prev_plan = faults_mod.install(plan, journal=journal)

    @classmethod
    def from_args(cls, args, journal) -> "Harness":
        """Build from the shared execution flags (``_add_execution``).
        ``--inject-faults`` wins over ``SPECPRIDE_FAULTS``; the env var
        exists so subprocess tests can arm a child run."""
        spec = getattr(args, "inject_faults", None)
        seed = int(getattr(args, "fault_seed", 0) or 0)
        plan = (
            FaultPlan.parse(spec, seed=seed)
            if spec else FaultPlan.from_env()
        )
        policy = RetryPolicy(
            retries=getattr(args, "retries", 0),
            backoff=getattr(args, "retry_backoff", 0.05),
            seed=seed, journal=journal,
        )
        timeout = float(getattr(args, "watchdog_timeout", 0.0) or 0.0)
        watchdog = (
            Watchdog(
                timeout, journal=journal,
                on_stall=plan.cancel_hangs if plan is not None else None,
            )
            if timeout > 0 else None
        )
        return cls(
            plan, policy, watchdog,
            degrade=not getattr(args, "no_degrade", False),
            journal=journal,
        )

    @property
    def armed(self) -> bool:
        return self.plan is not None

    def check(self, site: str) -> None:
        if self.plan is not None:
            self.plan.check(site)

    def retry_call(self, site: str, fn, *, before_retry=None):
        return self.policy.call(site, fn, before_retry=before_retry)

    def section(self, lane: str):
        if self.watchdog is not None:
            return self.watchdog.section(lane)
        return contextlib.nullcontext()

    def note_degrade(self, action: str, reason: str, chunk_index: int,
                     n_clusters: int) -> None:
        with self._lock:
            if action == "split":
                self.degrade_splits += 1
            else:
                self.degrade_reroutes += 1
        if self.journal is not None:
            self.journal.emit(
                "degrade", action=action, reason=reason,
                chunk_index=chunk_index, n_clusters=n_clusters,
            )

    def note_repair(self) -> None:
        with self._lock:
            self.resume_repairs += 1

    def summary(self, quarantined: int = 0) -> dict | None:
        """The ``run_end.robustness`` payload — None when the whole
        layer stayed dormant (nothing armed, nothing fired), so
        fault-free runs keep their historical run_end shape."""
        out: dict = {}
        if self.plan is not None:
            out["faults"] = self.plan.summary()
        retries = self.policy.summary()
        if self.armed or retries["retries"]:
            out.update(retries)
        if self.degrade_splits or self.degrade_reroutes:
            out["degrade_splits"] = self.degrade_splits
            out["degrade_reroutes"] = self.degrade_reroutes
        if self.resume_repairs:
            out["resume_repairs"] = self.resume_repairs
        if quarantined:
            out["quarantined"] = quarantined
        if self.watchdog is not None and self.watchdog.stall_count:
            out["watchdog_stalls"] = self.watchdog.stall_count
        return out or None

    def close(self) -> None:
        faults_mod.install(self._prev_plan)
        self._prev_plan = None
        if self.watchdog is not None:
            self.watchdog.stop()
