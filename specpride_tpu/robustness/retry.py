"""Bounded retry with exponential backoff and deterministic jitter.

One :class:`RetryPolicy` per run wraps the failure-prone lane tails:
the pack stage, chunk dispatch, the QC cosine pass, and the committer's
MGF append + manifest replace.  Only errors the shared taxonomy calls
transient (``errors.is_transient``) retry — malformed input fails fast
to ``--on-error``, exactly as before this layer existed.

Jitter is deterministic (``sha256(seed, site, attempt)``), so a seeded
fault-injection run backs off identically every time: chaos CI wall
times are reproducible and a flaking recovery path can be replayed.
The policy is shared across lanes and therefore thread-safe; counters
land in ``run_end.robustness`` via :meth:`summary`.
"""

from __future__ import annotations

import hashlib
import threading
import time

from specpride_tpu.observability import logger
from specpride_tpu.robustness import errors


class RetryPolicy:
    """``--retries N --retry-backoff BASE``: up to N retries per call,
    sleeping ``BASE * 2**attempt * (1 + jitter)`` between attempts with
    ``jitter`` drawn deterministically in [0, 0.25)."""

    def __init__(self, retries: int = 0, backoff: float = 0.05,
                 seed: int = 0, journal=None):
        self.retries = max(int(retries), 0)
        self.backoff = max(float(backoff), 0.0)
        self.seed = int(seed)
        self.journal = journal
        self._lock = threading.Lock()
        self.retry_count = 0
        self.retry_wait_s = 0.0
        self.retries_by_site: dict[str, int] = {}

    def _jitter(self, site: str, attempt: int) -> float:
        digest = hashlib.sha256(
            f"{self.seed}:{site}:{attempt}".encode()
        ).digest()
        return 0.25 * int.from_bytes(digest[:8], "big") / float(1 << 64)

    def backoff_s(self, site: str, attempt: int) -> float:
        """Sleep before retry number ``attempt`` (0-based)."""
        return self.backoff * (2 ** attempt) * (
            1.0 + self._jitter(site, attempt)
        )

    def note_retry(self, site: str, attempt: int, error: BaseException,
                   wait_s: float) -> None:
        with self._lock:
            self.retry_count += 1
            self.retry_wait_s += wait_s
            self.retries_by_site[site] = (
                self.retries_by_site.get(site, 0) + 1
            )
        if self.journal is not None:
            self.journal.emit(
                "retry", site=site, attempt=attempt,
                backoff_s=round(wait_s, 4),
                error=f"{type(error).__name__}: {error}",
            )
        logger.warning(
            "%s failed (%s); retry %d/%d in %.3fs",
            site, error, attempt + 1, self.retries, wait_s,
        )

    def call(self, site: str, fn, *, before_retry=None):
        """Run ``fn()``; on a transient error, wait and re-run, up to
        ``retries`` times.  ``before_retry`` (if given) runs before each
        re-attempt — the committer uses it to truncate a partial append
        so the retry can never duplicate bytes.  The final error (or
        any permanent error) propagates to the caller's policy."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - classified below
                if attempt >= self.retries or not errors.is_transient(e):
                    raise
                wait = self.backoff_s(site, attempt)
                self.note_retry(site, attempt, e, wait)
                if before_retry is not None:
                    before_retry()
                if wait > 0:
                    time.sleep(wait)
                attempt += 1

    def summary(self) -> dict:
        with self._lock:
            return {
                "retries": self.retry_count,
                "retry_wait_s": round(self.retry_wait_s, 4),
                "retries_by_site": dict(sorted(
                    self.retries_by_site.items()
                )),
            }
