"""Seeded, deterministic fault injection at named pipeline sites.

A :class:`FaultPlan` is parsed from ``--inject-faults``
``SITE:KIND:RATE[:AFTER[:MAX]],...`` (or the ``SPECPRIDE_FAULTS`` env
var, so subprocess kill/resume tests can arm a child run without
threading CLI flags through):

* ``SITE`` — one of :data:`FAULT_SITES`, the lane boundaries already
  delimited by tracing spans: ``parse`` (chunk materialization / MGF
  window parse), ``pack`` (host pack stage), ``prepare`` (backend
  ``prepare_chunk``), ``dispatch`` (device dispatch of a chunk),
  ``d2h`` (device→host result fetch), ``qc`` (cosine QC pass),
  ``write`` (MGF append), ``checkpoint_write`` (manifest replace).
* ``KIND`` — the realistic error raised there: ``io`` (``OSError``),
  ``oom`` (a ``RESOURCE_EXHAUSTED``-shaped ``RuntimeError``, the shape
  jaxlib's ``XlaRuntimeError`` carries), ``malformed``
  (``ValueError``), or ``hang`` (the site blocks until the per-lane
  watchdog cancels it — or a hard bound expires — then raises a
  transient :class:`~specpride_tpu.robustness.errors.LaneHangError`).
* ``RATE`` — firing probability per eligible visit, drawn
  deterministically from ``sha256(seed, site, visit)`` so a given
  ``(plan, seed)`` fires at exactly the same visits on every run,
  regardless of thread scheduling.
* ``AFTER`` — skip the first AFTER visits of the site (default 0), so
  a fault can target "the third chunk" instead of the first.
* ``MAX`` — cap on total fires for this entry (default 1).  The cap is
  what makes ``RATE=1`` useful: "fire exactly once, as early as
  possible", the chaos-CI idiom — and it guarantees a bounded retry
  policy eventually sees a clean attempt.

Every fired fault is journaled as a ``fault`` event before the error
is raised, so a post-mortem can pair each injection with the recovery
event (``retry`` / ``degrade`` / ``resume_repair`` / ``quarantine`` /
``skipped_clusters``) that survived it — :func:`audit_fault_recovery`
implements exactly that pairing for CI.

The plan installs process-globally (:func:`install`) because the
injection points live in both the CLI executor and the backends;
:func:`check` is the single hot-path entry and costs one global read
when no plan is armed.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time

from specpride_tpu.robustness.errors import InjectedFault, LaneHangError

# the chunk executor's lane-boundary sites — every chunked run visits
# all of these, which is what the ci.sh chaos matrix asserts
EXECUTOR_FAULT_SITES = (
    "parse", "pack", "prepare", "dispatch", "d2h", "qc", "write",
    "checkpoint_write",
)

# all injectable sites: the executor lanes plus the elastic
# coordinator's compare-and-swap ops (`cas` fires only in --elastic
# runs — the preemption-storm CI pass owns exercising it)
FAULT_SITES = EXECUTOR_FAULT_SITES + ("cas",)

FAULT_KINDS = (
    "io", "oom", "malformed", "hang", "rank_kill", "rank_slow",
    "cas_conflict",
)

# a hang with no watchdog armed must still end: hard bound on the block
MAX_HANG_S = 5.0

# per-visit stall of the `rank_slow` kind (a degraded-but-alive host:
# thermal throttling, a noisy neighbour, a failing disk).  Unlike
# `hang` it raises NOTHING and the watchdog must not break it — the
# point is to force the elastic tier's work-stealing, not a retry.
# Overridable for chaos scenarios via SPECPRIDE_SLOW_S.
DEFAULT_SLOW_S = 0.5

# fault kinds that perturb the run without failing anything: no
# recovery event is expected, so audit_fault_recovery must not flag
# them (a rank_slow rank still commits every chunk — just late; the
# recovery it forces, a lease_split, is audited by audit_elastic)
_SELF_RECOVERING_KINDS = frozenset({"rank_slow"})

# which retry-wrapper site recovers a fault fired at SITE: the pack-lane
# wrapper covers everything the pack stage runs (materialization,
# prepare); the dispatch wrapper covers the device round trip incl. the
# result fetch.  audit_fault_recovery pairs events with this map.
_RECOVERY_SITES = {
    "parse": ("pack",),
    "pack": ("pack",),
    "prepare": ("pack",),
    "dispatch": ("dispatch",),
    "d2h": ("dispatch",),
    "qc": ("qc",),
    "write": ("write",),
    "checkpoint_write": ("checkpoint_write",),
    # coordinator compare-and-swap races: the recovery is the
    # coordinator's own conflict handler (lose gracefully, re-scan),
    # journaled as a zero-backoff retry at the same site
    "cas": ("cas",),
}


def recovery_sites_for(site: str) -> tuple[str, ...]:
    return _RECOVERY_SITES.get(site, (site,))


class InjectedOSError(OSError, InjectedFault):
    pass


class InjectedResourceExhausted(RuntimeError, InjectedFault):
    """Shaped like jaxlib's XlaRuntimeError for RESOURCE_EXHAUSTED — the
    message prefix is what ``errors.is_oom`` (and production code
    matching real device OOMs) keys on."""


class InjectedValueError(ValueError, InjectedFault):
    pass


class InjectedHang(LaneHangError, InjectedFault):
    pass


class InjectedCasConflict(RuntimeError, InjectedFault):
    """A coordinator compare-and-swap lost its race (injected stand-in
    for a real 412/EEXIST under contention).  The coordinator catches
    it at the op boundary and loses gracefully — it never propagates
    into the executor."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    site: str
    kind: str
    rate: float
    after: int = 0
    max_fires: int = 1

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        parts = text.strip().split(":")
        if not 3 <= len(parts) <= 5:
            raise ValueError(
                f"fault spec {text!r}: want SITE:KIND:RATE[:AFTER[:MAX]]"
            )
        site, kind, rate = parts[0], parts[1], float(parts[2])
        if site not in FAULT_SITES:
            raise ValueError(
                f"fault spec {text!r}: unknown site {site!r} "
                f"(sites: {', '.join(FAULT_SITES)})"
            )
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"fault spec {text!r}: unknown kind {kind!r} "
                f"(kinds: {', '.join(FAULT_KINDS)})"
            )
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"fault spec {text!r}: rate must be in [0, 1]")
        after = int(parts[3]) if len(parts) >= 4 else 0
        max_fires = int(parts[4]) if len(parts) == 5 else 1
        if after < 0 or max_fires < 0:
            raise ValueError(f"fault spec {text!r}: AFTER/MAX must be >= 0")
        return cls(site, kind, rate, after, max_fires)


class FaultPlan:
    """The armed set of fault specs plus per-site visit/fire accounting.

    Thread-safe: ``check`` is called concurrently from pack workers, the
    dispatch lane, the committer, and backend fetch threads.  The visit
    counter advances under the lock; the fire decision is a pure
    function of ``(seed, site, visit)`` so concurrency changes *which
    thread* trips a fault, never *which visit* does."""

    def __init__(self, specs: list[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)
        self.journal = None  # attached by install(); may stay None
        self._by_site: dict[str, list[FaultSpec]] = {}
        for s in self.specs:
            self._by_site.setdefault(s.site, []).append(s)
        self._lock = threading.Lock()
        self._visits: dict[str, int] = {}
        self._fires: dict[int, int] = {}  # spec index -> fire count
        self._spec_index = {id(s): i for i, s in enumerate(self.specs)}
        self.fired_by_site: dict[str, int] = {}
        self._hang_cancel = threading.Event()

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        specs = [
            FaultSpec.parse(part)
            for part in text.split(",")
            if part.strip()
        ]
        if not specs:
            raise ValueError(f"empty fault plan {text!r}")
        return cls(specs, seed=seed)

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        """``SPECPRIDE_FAULTS`` / ``SPECPRIDE_FAULT_SEED``: the subprocess
        escape hatch (kill/resume tests arm a child CLI run without
        plumbing flags through its argv)."""
        spec = os.environ.get("SPECPRIDE_FAULTS", "").strip()
        if not spec:
            return None
        seed = int(os.environ.get("SPECPRIDE_FAULT_SEED", "0") or 0)
        return cls.parse(spec, seed=seed)

    @property
    def fired_total(self) -> int:
        return sum(self.fired_by_site.values())

    def summary(self) -> dict:
        return {
            "plan": [dataclasses.asdict(s) for s in self.specs],
            "seed": self.seed,
            "fired_total": self.fired_total,
            "fired_by_site": dict(sorted(self.fired_by_site.items())),
        }

    def _draw(self, site: str, visit: int) -> float:
        digest = hashlib.sha256(
            f"{self.seed}:{site}:{visit}".encode()
        ).digest()
        return int.from_bytes(digest[:8], "big") / float(1 << 64)

    def cancel_hangs(self) -> None:
        """Break every current AND future injected hang — the watchdog's
        lever.  One-way by design: once a run's watchdog has proven the
        lane can stall, further hangs at the same sites would only
        re-measure the same timeout."""
        self._hang_cancel.set()

    def check(self, site: str) -> None:
        """Fire at most one armed fault for this visit of ``site``.

        Raises the fault's error type after journaling a ``fault``
        event; a clean visit returns immediately (one lock + one dict
        update when specs exist for the site, a dict miss otherwise)."""
        specs = self._by_site.get(site)
        fired: FaultSpec | None = None
        with self._lock:
            visit = self._visits.get(site, 0)
            self._visits[site] = visit + 1
            if specs:
                for s in specs:
                    idx = self._spec_index[id(s)]
                    if visit < s.after:
                        continue
                    if self._fires.get(idx, 0) >= s.max_fires:
                        continue
                    if self._draw(site, visit) < s.rate:
                        self._fires[idx] = self._fires.get(idx, 0) + 1
                        self.fired_by_site[site] = (
                            self.fired_by_site.get(site, 0) + 1
                        )
                        fired = s
                        break
        if fired is None:
            return
        if self.journal is not None:
            self.journal.emit(
                "fault", site=site, kind=fired.kind, visit=visit,
            )
        self._raise(site, fired, visit)

    def _raise(self, site: str, spec: FaultSpec, visit: int) -> None:
        msg = f"injected {spec.kind} fault at {site} (visit {visit})"
        if spec.kind == "rank_slow":
            # a slow-but-alive rank: stall this visit, then CONTINUE —
            # nothing fails, heartbeats keep renewing the lease, and
            # the per-chunk wall the rank publishes climbs until a
            # peer's work-stealing handshake relieves it.  Deliberately
            # immune to the watchdog's hang-cancel: slowness is not a
            # stall the lane can break.
            try:
                slow_s = float(os.environ.get("SPECPRIDE_SLOW_S", "") or 0)
            except ValueError:
                slow_s = 0.0
            time.sleep(slow_s if slow_s > 0 else DEFAULT_SLOW_S)
            return
        if spec.kind == "cas_conflict":
            raise InjectedCasConflict(msg)
        if spec.kind == "rank_kill":
            # chaos-CI rank death: SIGKILL this process at a site
            # boundary — no handlers, no atexit, no flushes beyond the
            # journal line emitted above (line-buffered, already on
            # disk).  The recovery evidence lives in a SURVIVING rank's
            # journal: its lease_expire + chunk_reassign pair.
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(MAX_HANG_S)  # unreachable: SIGKILL cannot be caught
        if spec.kind == "io":
            raise InjectedOSError(msg)
        if spec.kind == "oom":
            raise InjectedResourceExhausted(f"RESOURCE_EXHAUSTED: {msg}")
        if spec.kind == "malformed":
            raise InjectedValueError(msg)
        # hang: block until the watchdog cancels us (or the hard bound
        # expires), then surface as a transient lane-hang the enclosing
        # retry policy recovers — exactly what a real stalled device
        # stream looks like from the lane's point of view
        deadline = time.perf_counter() + MAX_HANG_S
        while time.perf_counter() < deadline:
            if self._hang_cancel.wait(timeout=0.02):
                break
        raise InjectedHang(f"{msg}: lane unblocked after stall")


_active: FaultPlan | None = None
_suppress = threading.local()


class _Suppressed:
    """Context manager disabling injection on THIS thread — used by the
    degradation reroute: its numpy fallback is a different physical path
    than the device lane the plan models, and injecting into the
    last-resort recovery would only prove that no recovery remains."""

    def __enter__(self):
        self._prev = getattr(_suppress, "on", False)
        _suppress.on = True
        return self

    def __exit__(self, *exc):
        _suppress.on = self._prev


def suppressed() -> _Suppressed:
    return _Suppressed()


def install(plan: FaultPlan | None, journal=None) -> FaultPlan | None:
    """Arm ``plan`` process-wide (None disarms).  Returns the previous
    plan so callers can restore it — the CLI arms per run and disarms in
    its ``finally``."""
    global _active
    prev = _active
    if plan is not None and journal is not None:
        plan.journal = journal
    _active = plan
    return prev


def uninstall() -> None:
    install(None)


def active_plan() -> FaultPlan | None:
    return _active


def check(site: str) -> None:
    """THE injection hot path, called at every site on every chunk.
    Disarmed cost is one global read and a None test — measured in the
    bench's ``fault_overhead`` section."""
    plan = _active
    if plan is not None and not getattr(_suppress, "on", False):
        plan.check(site)


def audit_fault_recovery(events: list[dict]) -> list[dict]:
    """Pair every journaled ``fault`` with a later recovery event.

    Recovery evidence, in pairing order: a ``retry`` at the fault
    site's wrapper (see :func:`recovery_sites_for`), a ``degrade``, a
    ``quarantine``, a ``resume_repair``, a ``chunk_reassign`` (a
    surviving elastic rank reclaimed a killed rank's range — feed the
    MERGED per-rank journals, the reassignment never lives in the dead
    rank's own file), or a ``skipped_clusters`` record (the
    ``--on-error skip`` outcome).  Each recovery event backs at most
    one fault.  Returns the faults left unmatched — the chaos CI pass
    asserts this list is empty."""
    faults = [
        e for e in events
        if e.get("event") == "fault"
        and e.get("kind") not in _SELF_RECOVERING_KINDS
    ]
    recoveries = [
        e for e in events
        if e.get("event") in (
            "retry", "degrade", "quarantine", "resume_repair",
            "skipped_clusters", "chunk_reassign",
        )
    ]
    used: set[int] = set()
    unmatched = []
    for f in faults:
        sites = recovery_sites_for(f.get("site", ""))
        found = False
        for i, r in enumerate(recoveries):
            if i in used:
                continue
            if r["event"] == "chunk_reassign":
                # a reassignment only evidences recovery from a rank
                # DEATH: pairing it with other fault kinds would let a
                # natural slow-rank reassignment mask a genuinely
                # unrecovered io/oom fault.  No mono check either way —
                # it lives in a DIFFERENT rank's journal (per-process
                # mono is incomparable) and is inherently later than
                # the death it recovers.
                if f.get("kind") != "rank_kill":
                    continue
            elif r.get("mono", 0) < f.get("mono", 0):
                # in-process recoveries must follow the fault
                continue
            if r["event"] == "retry" and r.get("site") not in sites:
                continue
            used.add(i)
            found = True
            break
        if not found:
            unmatched.append(f)
    return unmatched
